"""Serving-tier tests (ISSUE 17): networked job API, read-side snapshot
query service, deadline-aware admission, pluggable queue backend.

The acceptance bar, tier-1: a job submitted over HTTP runs to
completion under a live `MeshScheduler` and ends bit-identical to its
CLI-submitted twin while cancel and resize arrive over HTTP; a
committed snapshot answers a sub-box HTTP query byte-identical to
`Snapshot.read_global` with the block LRU hitting on the second read;
an over-deadline job is REJECTED at admission with a journaled
`predict_step`-priced verdict `service_report` reproduces; and two
schedulers sharing one backend admit ≥20 jobs with zero
double-admissions (atomic-rename claim).

Budget note (ROADMAP tier-1): one fast representative per behavior;
the 20-job partition runs backend-only (no mesh); matrices ride `slow`.
"""

import io
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.serve import (
    BlockCache, CachedSnapshot, JobApiServer, SnapshotQueryServer,
)
from implicitglobalgrid_tpu.service import (
    DirectoryBackend, JobState, MeshScheduler, QueueBackend,
    jobspec_from_json,
)
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)

from conftest import (
    health_counters_from_registry as _health_counters,
    reset_health_counters_in_registry as _reset_health_counters,
)

GRID_A = dict(nx=6, ny=6, nz=6, dimx=2, dimy=2, dimz=1)


def _record(name, nt=8, nt_chunk=4, **extra):
    """One queue-JSON job record — THE schema of `tools jobs submit`
    and ``POST /v1/jobs`` (float64: the tier-1 x64 default, so interiors
    compare bit-exactly)."""
    rec = {"name": name, "model": "diffusion3d", "nt": nt,
           "grid": dict(GRID_A), "dtype": "float64",
           "run": {"nt_chunk": nt_chunk}}
    rec.update(extra)
    return rec


def _interior(sched, name):
    """Gathered interior of a finished job's result, under ITS grid."""
    from implicitglobalgrid_tpu.parallel import topology as top

    job = sched.job(name)
    prev = top.swap_global_grid(job.gg)
    try:
        return igg.gather_interior(job.result["T"])
    finally:
        top.swap_global_grid(prev)


_TWIN_CACHE: dict = {}


def _twin_interior(tmp_path, nt=8, nt_chunk=4):
    """The CLI-submitted twin: the same queue record pushed through
    `jobspec_from_json` + a solo scheduler (exactly the `tools jobs
    submit` code path). Memoized — several tenants compare against one
    reference."""
    key = (nt, nt_chunk)
    if key in _TWIN_CACHE:
        return _TWIN_CACHE[key]
    with MeshScheduler(flight_dir=str(tmp_path / "twin")) as sched:
        sched.submit(jobspec_from_json(_record("twin", nt, nt_chunk)))
        sched.run()
        assert sched.job("twin").state == JobState.DONE
        ref = _interior(sched, "twin")
    _TWIN_CACHE[key] = ref
    return ref


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers)


def _post(url, payload=None, timeout=10):
    body = b"" if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# Public API / exports
# ---------------------------------------------------------------------------

def test_public_api_exports():
    for sym in ("serve", "JobApiServer", "SnapshotQueryServer",
                "BlockCache", "CachedSnapshot",
                "ObservePlane", "ObserveServer"):
        assert hasattr(igg, sym), sym
        assert sym in igg.__all__, sym
    from implicitglobalgrid_tpu import service

    for sym in ("QueueBackend", "DirectoryBackend", "jobspec_from_json"):
        assert hasattr(service, sym), sym


# ---------------------------------------------------------------------------
# Queue backend: atomic claim partition (host-only — the >= 20-job bar)
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_two_owner_claim_partition_no_double_admission(tmp_path):
    """Two consumers over ONE directory backend: every record is
    claimed by exactly one owner (atomic rename), none twice, none
    lost — across 20 jobs."""
    b1 = DirectoryBackend(tmp_path, owner="s1")
    b2 = DirectoryBackend(tmp_path, owner="s2")
    names = [f"job{i:02d}" for i in range(20)]
    for n in names:
        b1.submit(_record(n))
    assert b2.pending() == sorted(names)
    with pytest.raises(InvalidArgumentError, match="already enqueued"):
        b2.submit(_record(names[0]))

    claims = {"s1": [], "s2": []}
    backends = [("s1", b1), ("s2", b2)]
    i = 0
    while True:
        owner, b = backends[i % 2]
        i += 1
        got = b.claim()
        if got is None:
            if all(b.claim() is None for _, b in backends):
                break
            continue
        assert got["record"]["name"] == got["name"]
        claims[owner].append(got["name"])
    assert not set(claims["s1"]) & set(claims["s2"])  # zero double-claims
    assert sorted(claims["s1"] + claims["s2"]) == sorted(names)
    assert claims["s1"] and claims["s2"]  # both actually took work
    # a claimed record cannot be discarded; a fresh pending one can
    assert b1.discard(names[0]) is False
    b1.submit(_record("late"))
    assert b2.discard("late") is True
    assert b1.pending() == []


@pytest.mark.serve
def test_backend_control_protocol_roundtrip(tmp_path):
    """The control channel is the PR-8 file protocol verbatim: drain /
    cancel_<name> / resize_<name> under ``<root>/control/``, consumed
    in filing order; unreadable resize payloads surface as None."""
    b = DirectoryBackend(tmp_path)
    b.control("cancel", "a")
    b.control("drain")
    b.control("resize", "b", {"new_dims": [1, 2, 2], "via": "auto"})
    (tmp_path / "control" / "resize_torn").write_text("{not json")
    (tmp_path / "control" / "resize_staged.tmp").write_text("{}")
    reqs = DirectoryBackend(tmp_path).poll_control()
    assert {r["request"] for r in reqs} == {"drain", "cancel", "resize"}
    by = {(r["request"], r.get("job")): r for r in reqs}
    assert by[("resize", "b")]["payload"] == {"new_dims": [1, 2, 2],
                                              "via": "auto"}
    assert by[("resize", "torn")]["payload"] is None
    assert ("resize", "staged") not in by  # .tmp staging skipped
    assert b.poll_control() == []  # consumed
    with pytest.raises(InvalidArgumentError, match="payload"):
        b.control("resize", "x")
    with pytest.raises(InvalidArgumentError, match="Unknown control"):
        b.control("pause", "x")
    with pytest.raises(InvalidArgumentError, match="QueueBackend"):
        MeshScheduler(queue="nope")
    assert isinstance(b, QueueBackend)


# ---------------------------------------------------------------------------
# Block cache (host-only)
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_block_cache_lru_eviction_and_stats():
    blk = lambda: np.zeros(128, dtype=np.float64)  # 1 KiB
    c = BlockCache(max_bytes=3 * 1024)
    for k in ("a", "b", "c"):
        assert c.get(k) is None
        c.put(k, blk())
    assert c.get("a") is not None  # freshen a; b is now LRU
    c.put("d", blk())
    assert c.get("b") is None and c.get("a") is not None
    st = c.stats()
    assert st["entries"] == 3 and st["bytes"] == 3 * 1024
    assert st["evictions"] == 1 and st["hits"] == 2
    c.put("huge", np.zeros(4096, dtype=np.float64))  # > whole budget
    assert c.get("huge") is None and c.stats()["entries"] == 3
    c.clear()
    assert c.stats()["entries"] == 0 and c.stats()["bytes"] == 0
    with pytest.raises(InvalidArgumentError, match="positive"):
        BlockCache(0)
    with pytest.raises(InvalidArgumentError, match="BlockCache"):
        CachedSnapshot("/nonexistent", cache="nope")


# ---------------------------------------------------------------------------
# Reader coherence: staging dirs refused, torn containers typed
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.io
def test_reader_refuses_staging_and_torn_snapshot_dirs(tmp_path):
    igg.init_global_grid(**GRID_A, quiet=True)
    T = igg.zeros_g()
    root = tmp_path / "snaps"
    igg.write_snapshot(str(root), step=1, state={"T": T})
    step, path = igg.list_snapshots(str(root))[0]

    # a staging dir (writer mid-commit) is refused with the typed error
    import shutil

    stage = root / "step_0000000007.tmp-deadbeef"
    shutil.copytree(path, stage)
    with pytest.raises(IncoherentArgumentError, match="staging"):
        igg.open_snapshot(str(stage))
    # ... and list_snapshots never offers it
    assert [s for s, _ in igg.list_snapshots(str(root))] == [1]

    # a half-committed container (truncated meta, no sidecar — the
    # pre-checksum worst case) raises the typed refusal, not zipfile's
    torn = root / "step_0000000009"
    shutil.copytree(path, torn)
    (torn / "meta.npz").write_bytes(b"PK\x03\x04 truncated")
    (torn / "meta.npz.sha256").unlink()
    with pytest.raises(IncoherentArgumentError, match="half-committed"):
        igg.open_snapshot(str(torn))
    igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# THE acceptance test: HTTP submit -> live scheduler -> HTTP control ->
# bit-identity -> snapshot query with LRU hit
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.service
def test_http_job_e2e_bit_identical_with_query_service(tmp_path):
    """Three jobs POSTed to the job API run under a live scheduler
    polling the same backend: h1 (snapshotting) ends bit-identical to
    its CLI-submitted twin, h2 is elastically resized over HTTP, h3 is
    cancelled over HTTP mid-run; then the query service answers a
    sub-box read of h1's committed snapshot byte-identical to
    `read_global`, from the LRU on the second read."""
    d = str(tmp_path / "svc")
    snapdir = str(tmp_path / "snaps_h1")
    ref = _twin_interior(tmp_path)

    with JobApiServer(d) as api, \
            MeshScheduler(policy="round_robin", flight_dir=d) as sched:
        u = f"http://{api.host}:{api.port}"
        code, rec = _post(u + "/v1/jobs", {"jobs": [
            _record("h1", run={"nt_chunk": 4, "snapshot_dir": snapdir,
                               "snapshot_every": 4}),
            _record("h2"),
            _record("h3"),
        ]})
        assert (code, rec["submitted"]) == (202, ["h1", "h2", "h3"])
        _, body, _ = _get(u + "/v1/jobs")
        jobs = json.loads(body)["jobs"]
        assert {n: j["state"] for n, j in jobs.items()} == {
            "h1": "pending", "h2": "pending", "h3": "pending"}
        # /metrics rides the same port (one ops surface per server)
        status, body, _ = _get(u + "/metrics")
        assert status == 200 and b"igg_" in body

        # the scheduler claims one record per decision; catch h2 and h3
        # RUNNING to land resize/cancel on the live control path
        def _step_until_running(name, budget=50):
            for _ in range(budget):
                if name in sched.jobs \
                        and sched.job(name).state == JobState.RUNNING:
                    return
                sched.step()
            raise AssertionError(f"{name} never reached RUNNING")

        _step_until_running("h2")
        code, rec = _post(u + "/v1/jobs/h2/resize",
                          {"new_dims": [1, 2, 2]})
        assert (code, rec["requested"]) == (202, "resize")
        _step_until_running("h3")
        code, rec = _post(u + "/v1/jobs/h3/cancel")
        assert (code, rec["requested"]) == (202, "cancel")
        assert "discarded" not in rec  # claimed: the control-file path
        sched.run()

        assert sched.job("h1").state == JobState.DONE
        assert sched.job("h2").state == JobState.DONE
        assert sched.job("h3").state == JobState.CANCELLED
        # the HTTP resize actually re-blocked h2's decomposition
        assert tuple(int(x) for x in sched.job("h2").gg.dims) == (1, 2, 2)
        # bit-identity: HTTP tenants == the CLI twin (resize is exact)
        assert np.array_equal(_interior(sched, "h1"), ref)
        assert np.array_equal(_interior(sched, "h2"), ref)

        # journal-derived status over HTTP agrees
        _, body, _ = _get(u + "/v1/jobs/h1")
        h1 = json.loads(body)
        assert h1["state"] == "done" and "claimed_by" in h1
        code, rec = _post(u + "/v1/jobs/h1/cancel")
        assert code == 409  # terminal
        code, rec = _post(u + "/v1/jobs/nope/cancel")
        assert code == 404

    # --- read side: the committed snapshots answer HTTP box reads ----------
    with SnapshotQueryServer(snapdir) as q:
        uq = f"http://{q.host}:{q.port}"
        _, body, _ = _get(uq + "/v1/snapshots")
        listing = json.loads(body)
        assert [s["step"] for s in listing["snapshots"]] == [4, 8]
        assert listing["snapshots"][0]["global_shapes"]["T"] == [10, 10, 6]

        box = (slice(1, 7), slice(2, 9), slice(0, 4))
        path8 = dict(igg.list_snapshots(snapdir))[8]
        expect = igg.open_snapshot(path8).read_global(
            "T", tuple((s.start, s.stop) for s in box))
        status, body, hdrs = _get(uq + "/v1/snapshots/8/T?box=1:7,2:9,0:4")
        arr = np.load(io.BytesIO(body))
        assert status == 200 and hdrs["X-IGG-Shape"] == "6,7,4"
        assert arr.dtype == np.float64
        assert np.array_equal(arr, expect)  # byte-identical to read_global
        # ... and to the final interior of the job that wrote it
        assert np.array_equal(arr, ref[box])

        # warm re-read: answered from the LRU, still byte-identical
        status, body2, hdrs2 = _get(
            uq + "/v1/snapshots/8/T?box=1:7,2:9,0:4")
        assert int(hdrs2["X-IGG-Cache-Hits"]) > 0
        assert int(hdrs["X-IGG-Cache-Hits"]) == 0
        assert body2 == body
        assert q.cache.stats()["hits"] > 0

        # point read + error surfaces
        _, body, _ = _get(uq + "/v1/snapshots/8/T?point=3,4,2")
        p = json.loads(body)
        assert p["value"] == float(ref[3, 4, 2])
        for bad, code in (("/v1/snapshots/8/T?box=banana", 400),
                          ("/v1/snapshots/8/T?box=0:2", 400),
                          ("/v1/snapshots/8/nope", 404),
                          ("/v1/snapshots/99/T", 404),
                          ("/v1/snapshots/8/T?box=0:2,0:2,0:2&point=1,1,1",
                           400)):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(uq + bad)
            assert ei.value.code == code, bad


# ---------------------------------------------------------------------------
# Deadline-aware admission + deadline_missed surfacing
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.service
def test_deadline_rejection_priced_and_journaled(tmp_path):
    """A job whose `predict_step` price provably busts its deadline is
    REJECTED at admission with the verdict journaled; an admissible
    deadline job runs (its run-level budget derived from the deadline),
    and a crossed run-level budget fires ONE deadline_missed event +
    counter. `service_report` reproduces all of it."""
    igg.reset_metrics()
    d = str(tmp_path / "svc")
    with MeshScheduler(policy="fifo", flight_dir=d) as sched:
        # provably over: ~1e7 modeled steps cannot fit half a second
        sched.submit(jobspec_from_json(
            _record("over", nt=10_000_000, nt_chunk=1_000_000,
                    deadline_s=0.5)))
        # admissible, generous deadline — but a tiny RUN-level budget,
        # so it finishes DONE with the miss surfaced
        sched.submit(jobspec_from_json(
            _record("ok", nt=4, nt_chunk=2, deadline_s=3600.0,
                    run={"nt_chunk": 2, "deadline_s": 1e-6})))
        sched.run()
        over = sched.job("over")
        assert over.state == JobState.REJECTED
        assert "admission rejected" in over.error
        assert sched.job("ok").state == JobState.DONE
        assert sched.job("ok").run.deadline_missed is True
    fam = igg.metrics_registry().get("igg_job_deadline_missed_total")
    assert fam is not None and fam.value() >= 1

    rep = igg.service_report(d)
    assert rep["states"] == {"rejected": 1, "done": 1}
    adm = rep["jobs"]["over"]["admission"]
    assert adm["verdict"] == "reject" and adm["priced_by"] == "predict_step"
    assert adm["admit_price_s"] > adm["budget_s"]
    assert adm["nt"] == 10_000_000 and adm["deadline_s"] == 0.5
    assert adm["step_price_s"] > 0 and adm["bound"]
    # the rejection message the API/CLI shows is the journaled verdict
    assert f"{adm['admit_price_s']:.3g}" in rep["jobs"]["over"]["error"]
    ok = rep["jobs"]["ok"]
    assert ok["admission"]["verdict"] == "admit"
    assert ok["deadline_missed"]["deadline_s"] == 1e-6
    assert ok["state"] == "done"


@pytest.mark.serve
def test_deadline_validation_and_unpriceable_jobs_admit(tmp_path):
    """deadline_s must be positive wherever it appears; a job the model
    CANNOT price (custom setup, no model name) is admitted — admission
    only rejects what it can prove — with the unpriceable verdict
    journaled."""
    from implicitglobalgrid_tpu.service import JobSpec

    with pytest.raises(InvalidArgumentError, match="deadline_s"):
        jobspec_from_json(_record("x", deadline_s=-1.0))

    def _setup():
        from implicitglobalgrid_tpu.models import (
            diffusion_step_local, init_diffusion3d,
        )

        T, Cp, p = init_diffusion3d(dtype=np.float64)

        def step(s):
            return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                    "Cp": s["Cp"]}

        return step, {"T": T, "Cp": Cp}

    # the run-level budget is validated at driver construction
    igg.init_global_grid(**GRID_A, quiet=True)
    step, state = _setup()
    with pytest.raises(InvalidArgumentError, match="deadline_s"):
        igg.run_resilient(step, state, 2, nt_chunk=2, deadline_s=0.0)
    igg.finalize_global_grid()

    d = str(tmp_path / "svc")
    with MeshScheduler(flight_dir=d) as sched:
        sched.submit(JobSpec(
            name="custom", setup=_setup, nt=4, grid=GRID_A,
            deadline_s=0.5,  # tight — but unpriceable, so it runs
            run=igg.RunSpec(nt_chunk=2, key=("serve", "custom"))))
        sched.run()
        assert sched.job("custom").state == JobState.DONE
    adm = igg.service_report(d)["jobs"]["custom"]["admission"]
    assert adm["verdict"] == "admit" and adm["priced_by"] == "unpriceable"


# ---------------------------------------------------------------------------
# Two schedulers, one backend: partition + fault isolation + bit-identity
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.service
@pytest.mark.faults
def test_two_schedulers_share_backend_fault_isolated_bit_identical(
        tmp_path):
    """Two LIVE schedulers drain one queue: every record admitted by
    exactly one (journal-attributed claim), a NaNPoke in one tenant
    trips ITS guard only, and every tenant — both schedulers, recovery
    included — ends bit-identical to the CLI twin."""
    from implicitglobalgrid_tpu.service import JobSpec
    from implicitglobalgrid_tpu.service.job import builtin_setup

    ref = _twin_interior(tmp_path)
    _reset_health_counters()
    qroot = str(tmp_path / "q")
    b1 = DirectoryBackend(qroot, owner="s1")
    b2 = DirectoryBackend(qroot, owner="s2")
    for n in ("t1", "t2", "t3"):
        b1.submit(_record(n))
    d1, d2 = str(tmp_path / "svc1"), str(tmp_path / "svc2")
    with MeshScheduler(policy="round_robin", flight_dir=d1,
                       queue=b1) as s1, \
            MeshScheduler(policy="round_robin", flight_dir=d2,
                          queue=b2) as s2:
        # one direct-submitted faulty tenant on s1 (faults are live
        # objects — they ride JobSpec, not queue JSON)
        s1.submit(JobSpec(
            name="tfault", setup=builtin_setup("diffusion3d", "float64"),
            nt=8, grid=GRID_A, model="diffusion3d",
            run=igg.RunSpec(
                nt_chunk=4, key=("serve", "tfault"),
                checkpoint_dir=str(tmp_path / "ck"),
                faults=(igg.NaNPoke(step=6, name="T"),))))
        for _ in range(200):
            p1, p2 = s1.step(), s2.step()
            if not p1 and not p2 and not b1.pending():
                break
        assert not b1.pending()
        done = {}
        for sched in (s1, s2):
            for name, job in sched.jobs.items():
                assert job.state == JobState.DONE, (name, job.state)
                done[name] = _interior(sched, name)
        # zero double-admissions: the four tenants partitioned exactly
        assert len(done) == 4
        assert set(done) == {"t1", "t2", "t3", "tfault"}
        assert "tfault" in s1.jobs
        assert s1.jobs and s2.jobs  # both actually served tenants
        # the fault stayed in its tenant...
        c = _health_counters()
        assert c["guard_trips"] == 1 and c["rollbacks"] == 1
        # ... and EVERY tenant is bit-identical to the CLI twin
        for name, interior in done.items():
            assert np.array_equal(interior, ref), name

    # the journals attribute every claim to exactly one owner
    claimed = {}
    for dd in (d1, d2):
        for name, r in igg.service_report(dd)["jobs"].items():
            if "claimed_by" in r:
                assert name not in claimed, f"{name} claimed twice"
                claimed[name] = r["claimed_by"]
    assert set(claimed) == {"t1", "t2", "t3"}
    assert {v for v in claimed.values()} <= {"s1", "s2"}


# ---------------------------------------------------------------------------
# Job API validation (no mesh)
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_job_api_validation_and_status_merge(tmp_path):
    d = str(tmp_path / "svc")
    with JobApiServer(d) as api:
        u = f"http://{api.host}:{api.port}"
        code, rec = _post(u + "/v1/jobs", {"jobs": [{"name": "a"}]})
        assert code == 400 and "missing required" in rec["error"]
        code, rec = _post(
            u + "/v1/jobs", {"jobs": [_record("a"),
                                      _record("b", run={"bogus": 1})]})
        assert code == 400 and "bad 'run' knob" in rec["error"]
        assert api.backend.pending() == []  # nothing half-submitted
        # single-record form; duplicates 409 against queue AND batch
        code, rec = _post(u + "/v1/jobs", _record("a"))
        assert (code, rec["submitted"]) == (202, ["a"])
        code, rec = _post(u + "/v1/jobs", _record("a"))
        assert code == 409
        code, rec = _post(u + "/v1/jobs",
                          {"jobs": [_record("c"), _record("c")]})
        assert code == 409 and api.backend.pending() == ["a"]
        # resize validation; unknown routes/jobs
        code, rec = _post(u + "/v1/jobs/a/resize", {"new_dims": [1, 2]})
        assert code == 400 and "new_dims" in rec["error"]
        code, rec = _post(u + "/v1/jobs/a/resize",
                          {"new_dims": [1, 2, 2], "via": "magic"})
        assert code == 400 and "via" in rec["error"]
        code, rec = _post(u + "/v1/jobs/zzz/cancel")
        assert code == 404
        code, rec = _post(u + "/v1/nope")
        assert code == 404
        code, rec = _post(u + "/v1/jobs", None)
        assert code == 400
        # pending cancel = atomic discard, before any scheduler claims
        code, rec = _post(u + "/v1/jobs/a/cancel")
        assert (code, rec.get("discarded")) == (202, True)
        assert api.backend.pending() == []
        # drain files the global control request
        code, rec = _post(u + "/v1/drain")
        assert (code, rec["requested"]) == (202, "drain")
        assert DirectoryBackend(d).poll_control() == [{"request": "drain"}]


@pytest.mark.serve
def test_query_server_validation(tmp_path):
    with pytest.raises(InvalidArgumentError, match="root"):
        SnapshotQueryServer(str(tmp_path / "nope"))
    root = tmp_path / "empty"
    root.mkdir()
    with SnapshotQueryServer(str(root), cache_bytes=1024) as q:
        u = f"http://{q.host}:{q.port}"
        _, body, _ = _get(u + "/v1/snapshots")
        rec = json.loads(body)
        assert rec["snapshots"] == [] and rec["cache"]["max_bytes"] == 1024
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(u + "/v1/snapshots/3/T")
        assert ei.value.code == 404
        # write side is refused outright
        code, rec = _post(u + "/v1/snapshots")
        assert code == 405


# ---------------------------------------------------------------------------
# Live observability plane over HTTP (ISSUE 18): /v1/observe + /v1/events
# ---------------------------------------------------------------------------

def _obs_rec(kind, t, seq, **kw):
    return {"t": t, "kind": kind, "run": "j1", "pid": 1, "proc": 0,
            "seq": seq, **kw}


def _obs_append(path, recs):
    with open(path, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


@pytest.mark.serve
@pytest.mark.telemetry
def test_observe_endpoints_snapshot_and_resumable_stream(tmp_path):
    """The job API mounts the live plane over its own flight directory
    (``observe=True``, the default): ``/v1/observe`` serves the
    derived-signal snapshot, ``/v1/events`` streams the merged feed as
    chunked NDJSON — heartbeat-terminated, ``since=`` resumable, with a
    ``max_events`` cut whose final cursor resumes at the UNSENT tail —
    and a bad query is a 400, not a dead stream. The standalone
    `ObserveServer` serves the same plane without the job API;
    ``observe=False`` unmounts it."""
    from implicitglobalgrid_tpu.serve import ObserveServer

    d = str(tmp_path / "svc")
    os.makedirs(d)
    p = os.path.join(d, "flight_j1.jsonl")
    _obs_append(p, [
        _obs_rec("recorder_open", 100.0, 0, wall=5000.0),
        _obs_rec("chunk", 100.5, 1, chunk=0, step_begin=0, step_end=4,
                 n=4, ok=True, reasons=[], build_s=0.01, exec_s=0.4),
        _obs_rec("chunk", 101.0, 2, chunk=1, step_begin=4, step_end=8,
                 n=4, ok=True, reasons=[], build_s=0.01, exec_s=0.4),
        _obs_rec("deadline_slack", 101.1, 3, step=8, slack_s=-1.5),
    ])

    with JobApiServer(d) as api:
        u = f"http://{api.host}:{api.port}"
        # -- /v1/observe: the derived snapshot + the resume cursor ----------
        _, body, _ = _get(u + "/v1/observe")
        snap = json.loads(body)
        assert snap["cursor"] == 3
        j1 = snap["jobs"]["j1"]
        assert j1["deadline_slack_s"] == -1.5
        assert j1["step_s_p50"] == pytest.approx(0.1)
        # -- /v1/events: NDJSON, chunked, ends with a done-heartbeat --------
        status, body, hdrs = _get(
            u + "/v1/events?since=-1&timeout_s=0.2&heartbeat_s=0.05")
        assert status == 200
        assert hdrs["Content-Type"] == "application/x-ndjson"
        assert hdrs.get("Transfer-Encoding") == "chunked"
        lines = [json.loads(x) for x in body.splitlines()]
        evs = [e for e in lines if e["kind"] != "heartbeat"]
        assert [e["live_seq"] for e in evs] == [0, 1, 2, 3]
        assert [e["kind"] for e in evs] == [
            "recorder_open", "chunk", "chunk", "deadline_slack"]
        assert lines[-1]["kind"] == "heartbeat"
        assert (lines[-1]["cursor"], lines[-1]["done"]) == (3, True)
        # -- max_events cut: the final cursor resumes at the UNSENT tail ----
        _, body, _ = _get(
            u + "/v1/events?since=-1&max_events=2&timeout_s=5")
        lines = [json.loads(x) for x in body.splitlines()]
        assert [e.get("live_seq") for e in lines[:2]] == [0, 1]
        assert (lines[-1]["cursor"], lines[-1]["done"]) == (1, True)
        _, body, _ = _get(
            u + f"/v1/events?since={lines[-1]['cursor']}&timeout_s=0.2")
        lines = [json.loads(x) for x in body.splitlines()]
        assert [e["live_seq"] for e in lines
                if e["kind"] != "heartbeat"] == [2, 3]
        # -- a mid-stream append arrives on the next resumed request --------
        _obs_append(p, [
            _obs_rec("chunk", 101.5, 4, chunk=2, step_begin=8,
                     step_end=12, n=4, ok=True, reasons=[],
                     build_s=0.01, exec_s=0.4)])
        _, body, _ = _get(u + "/v1/events?since=3&timeout_s=0.2")
        lines = [json.loads(x) for x in body.splitlines()]
        assert [e["live_seq"] for e in lines
                if e["kind"] != "heartbeat"] == [4]
        # -- bad query: 400 JSON, not a dead stream -------------------------
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(u + "/v1/events?since=abc")
        assert ei.value.code == 400
        assert "bad /v1/events" in json.loads(ei.value.read())["error"]

    # the standalone server: same plane, no job API, /metrics rides along
    with ObserveServer(d) as obs:
        uo = f"http://{obs.host}:{obs.port}"
        _, body, _ = _get(uo + "/v1/observe")
        snap = json.loads(body)
        assert snap["jobs"]["j1"]["deadline_slack_s"] == -1.5
        assert snap["cursor"] == 4
        status, body, _ = _get(uo + "/metrics")
        assert status == 200 and b"igg_" in body

    # observe=False unmounts the plane (the job API alone)
    with JobApiServer(d, observe=False) as api2:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://{api2.host}:{api2.port}/v1/observe")
        assert ei.value.code == 404


# ---------------------------------------------------------------------------
# THE ISSUE-18 acceptance test: alerts fire under a live scheduler, a
# sink cancels the bust job at a slice boundary, survivors bit-identical
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.service
@pytest.mark.faults
def test_alerts_fire_sink_cancels_bust_job_survivors_bit_identical(
        tmp_path):
    """A live scheduler with the default rule pack + a `ControlFileSink`
    serves three tenants: a clean job, a NaNPoke'd job (one guard trip,
    recovered), and an admitted-but-over-budget job whose run-level
    deadline slack goes negative at its FIRST chunk boundary.
    ``guard_trip_storm`` and ``deadline_slack_burn`` FIRE — journaled
    with the right job attribution, counted in ``igg_alerts_total`` —
    while ``persistent_straggler`` stays silent (no in-process barrier
    view); the sink files the cancel control file the scheduler consumes
    at its next slice boundary, so the bust job dies CANCELLED mid-run;
    and the surviving tenants end bit-identical to the CLI twin. The
    journaled transitions then surface over HTTP: ``/v1/observe`` lists
    both alerts active, ``/v1/events`` streams the transitions."""
    from implicitglobalgrid_tpu.service import JobSpec
    from implicitglobalgrid_tpu.service.job import builtin_setup
    from implicitglobalgrid_tpu.serve import ObserveServer
    from implicitglobalgrid_tpu.telemetry.live import ControlFileSink

    ref = _twin_interior(tmp_path)
    _reset_health_counters()
    igg.reset_metrics()
    d = str(tmp_path / "svc")
    backend = DirectoryBackend(d)
    sink = ControlFileSink(backend, rules=("deadline_slack_burn",))
    with MeshScheduler(policy="round_robin", flight_dir=d, queue=backend,
                       alerts=True, alert_sinks=(sink,)) as sched:
        sched.submit(jobspec_from_json(_record("good")))
        # the fault rides JobSpec (live objects, not queue JSON)
        sched.submit(JobSpec(
            name="poked", setup=builtin_setup("diffusion3d", "float64"),
            nt=8, grid=GRID_A, model="diffusion3d",
            run=igg.RunSpec(
                nt_chunk=4, key=("serve", "poked"),
                checkpoint_dir=str(tmp_path / "ck"),
                faults=(igg.NaNPoke(step=6, name="T"),))))
        # admitted (generous SPEC deadline prices fine) but over budget
        # at RUN level: slack is negative from the first boundary on
        sched.submit(jobspec_from_json(
            _record("bust", deadline_s=3600.0,
                    run={"nt_chunk": 4, "deadline_s": 1e-6})))
        sched.run()

        assert sched.job("good").state == JobState.DONE
        assert sched.job("poked").state == JobState.DONE
        # the alert-driven control file killed bust at a slice boundary
        assert sched.job("bust").state == JobState.CANCELLED
        assert sched.job("bust").run.step < 8  # mid-run, not completed
        assert sink.filed == [{"rule": "deadline_slack_burn",
                               "job": "bust", "action": "cancel"}]
        # the fault tripped poked's guard exactly once (and recovered)
        c = _health_counters()
        assert c["guard_trips"] == 1 and c["rollbacks"] == 1
        # survivors bit-identical to the solo CLI twin
        assert np.array_equal(_interior(sched, "good"), ref)
        assert np.array_equal(_interior(sched, "poked"), ref)

    # -- the journal attributes every transition to the right job -----------
    rep = igg.service_report(d)
    alerts = rep["alerts"]
    fired = {(a["rule"], a["job"]) for a in alerts["active"]}
    assert ("deadline_slack_burn", "bust") in fired
    assert ("guard_trip_storm", "poked") in fired
    assert set(alerts["by_rule"]) == {"deadline_slack_burn",
                                      "guard_trip_storm"}
    assert alerts["by_rule"]["deadline_slack_burn"]["severity"] \
        == "critical"
    # ... and every transition is counted, per rule
    fam = igg.metrics_registry().get("igg_alerts_total")
    counted = {lbl["rule"] for lbl, v in fam.samples() if v > 0}
    assert counted == {"deadline_slack_burn", "guard_trip_storm"}

    # -- the HTTP surface shows the same picture ---------------------------
    with ObserveServer(d, backend=DirectoryBackend(d)) as obs:
        u = f"http://{obs.host}:{obs.port}"
        _, body, _ = _get(u + "/v1/observe")
        snap = json.loads(body)
        active = {(a["rule"], a.get("job"))
                  for a in snap["alerts"]["active"]}
        assert {("deadline_slack_burn", "bust"),
                ("guard_trip_storm", "poked")} <= active
        assert not any(a["rule"] == "persistent_straggler"
                       for a in snap["alerts"]["recent"])
        assert snap["jobs"]["bust"]["deadline_slack_s"] < 0
        _, body, _ = _get(u + "/v1/events?since=-1&timeout_s=0.3")
        trans = [e for e in (json.loads(x) for x in body.splitlines())
                 if e["kind"] == "alert"]
        assert {(e["rule"], e.get("job")) for e in trans} == {
            ("deadline_slack_burn", "bust"),
            ("guard_trip_storm", "poked")}


# ---------------------------------------------------------------------------
# THE ISSUE-20 acceptance test: one traceparent, HTTP submit -> claim ->
# admission -> slices -> flight chunks -> OTLP span tree
# ---------------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.service
@pytest.mark.telemetry
def test_traceparent_e2e_http_submit_to_otlp_span_tree(tmp_path):
    """A client traceparent POSTed with a job is echoed on the response,
    rides the queue record, roots the claiming scheduler's journal
    (``job_claimed`` = the job's root span, parented on the API's submit
    span), stamps every later journal event AND every flight chunk span
    with the same trace id — and `export_otlp` reconstructs the whole
    thing as ONE connected span tree. A second, headerless job gets a
    fresh minted trace, and when an alert cancels it the ``control``
    event's parent is the ALERT's span — causality across the
    alert->sink->control-file->scheduler hop."""
    from implicitglobalgrid_tpu.telemetry import (
        TraceContext, export_otlp, read_flight_events,
    )
    from implicitglobalgrid_tpu.telemetry.live import ControlFileSink

    d = str(tmp_path / "svc")
    client = TraceContext.new()

    with JobApiServer(d) as api:
        u = f"http://{api.host}:{api.port}"
        req = urllib.request.Request(
            u + "/v1/jobs",
            data=json.dumps(
                {"jobs": [_record("tr1", deadline_s=3600.0)]}).encode(),
            method="POST",
            headers={"traceparent": client.to_traceparent()})
        with urllib.request.urlopen(req, timeout=10) as r:
            code, rec = r.status, json.loads(r.read())
            echoed = r.headers.get("traceparent")
        assert code == 202 and rec["submitted"] == ["tr1"]
        assert rec["traceparent"] == echoed
        # the API span: same trace as the caller, its own span id
        api_ctx = TraceContext.parse(echoed)
        assert api_ctx.trace_id == client.trace_id
        assert api_ctx.span_id != client.span_id

        # no header -> a fresh trace is MINTED for the bust job
        code, rec = _post(u + "/v1/jobs", {"jobs": [
            _record("bust", deadline_s=3600.0,
                    run={"nt_chunk": 4, "deadline_s": 1e-6})]})
        assert code == 202
        bust_tid = TraceContext.parse(rec["traceparent"]).trace_id
        assert bust_tid != client.trace_id

    sink = ControlFileSink(DirectoryBackend(d),
                           rules=("deadline_slack_burn",))
    with MeshScheduler(policy="round_robin", flight_dir=d, alerts=True,
                       alert_sinks=(sink,)) as sched:
        sched.run()
        assert sched.job("tr1").state == JobState.DONE
        assert sched.job("bust").state == JobState.CANCELLED

    # -- the journal: every tr1 event shares the client's trace id ----------
    tid = client.trace_id
    journal = read_flight_events(os.path.join(d, "scheduler.jsonl"))
    tr1 = [e for e in journal if e.get("job") == "tr1"]
    assert tr1 and all(e.get("trace_id") == tid for e in tr1)
    assert {"job_claimed", "job_submitted", "job_admitted",
            "admission_priced", "slice", "job_done"} \
        <= {e["kind"] for e in tr1}
    claimed = [e for e in tr1 if e["kind"] == "job_claimed"]
    assert len(claimed) == 1
    # job_claimed IS the job's root span, child of the API's submit span
    assert claimed[0]["parent_span_id"] == api_ctx.span_id
    root = claimed[0]["span_id"]
    for e in tr1:
        if e["kind"] != "job_claimed":
            assert (e["parent_span_id"], e["trace_id"]) == (root, tid)
            assert e["span_id"] not in ("", root)

    # -- the flight stream: chunk spans joined the SAME trace ---------------
    flight = read_flight_events(os.path.join(d, "job_tr1.jsonl"))
    chunks = [e for e in flight if e["kind"] == "chunk"]
    assert chunks
    for e in chunks:
        assert (e["trace_id"], e["parent_span_id"]) == (tid, root)
    # ... while the stream header stays untraced (schema unchanged)
    assert flight[0]["kind"] == "recorder_open"
    assert "trace_id" not in flight[0]

    # -- alert->cancel causality on the bust trace --------------------------
    alerts = [e for e in journal if e.get("kind") == "alert"
              and e.get("job") == "bust"
              and e.get("rule") == "deadline_slack_burn"]
    assert alerts and all(e["trace_id"] == bust_tid for e in alerts)
    controls = [e for e in journal if e.get("kind") == "control"
                and e.get("job") == "bust"]
    assert controls and controls[0]["trace_id"] == bust_tid
    # the control event's parent IS the alert's span: "why was my job
    # cancelled" is one parent walk back to the rule that fired
    assert controls[0]["parent_span_id"] in {e["span_id"] for e in alerts}

    # -- OTLP: ONE connected span tree from the HTTP request down -----------
    doc = export_otlp(d, trace_id=tid)
    spans = [s for rs in doc["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert {s["name"] for s in spans} >= {
        "job_claimed", "admission_priced", "slice", "chunk", "job_done"}
    ids = {s["spanId"] for s in spans}
    assert len(ids) == len(spans)  # minted + synthesized: all unique
    roots = [s for s in spans if s.get("parentSpanId") not in ids]
    assert [s["name"] for s in roots] == ["job_claimed"]
    assert roots[0]["parentSpanId"] == api_ctx.span_id
    by_id = {s["spanId"]: s for s in spans}
    for s in spans:  # every span's parent walk terminates at the claim
        hops = 0
        while s["spanId"] != roots[0]["spanId"]:
            s = by_id[s["parentSpanId"]]
            hops += 1
            assert hops <= len(spans)


# ---------------------------------------------------------------------------
# Bearer-token auth (ISSUE 19 satellite): the routed ops surface
# ---------------------------------------------------------------------------

def _get_code(url, token=None):
    req = urllib.request.Request(url)
    if token is not None:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


def test_api_token_gates_routed_surface(tmp_path, monkeypatch):
    """With a bearer token configured, every ROUTED endpoint of the three
    front doors answers 401 without (or with a wrong) token and works
    with the right one; /metrics and /healthz stay open for probes and
    scrapers. The token comes from the ``api_token=`` argument or the
    ``IGG_API_TOKEN`` environment; ``api_token=False`` forces an
    unauthenticated server even with the env set."""
    from implicitglobalgrid_tpu.serve import ObserveServer

    monkeypatch.delenv("IGG_API_TOKEN", raising=False)
    d = str(tmp_path / "svc")
    with JobApiServer(d, api_token="s3cret") as api:
        u = f"http://{api.host}:{api.port}"
        assert _get_code(u + "/v1/jobs") == 401
        assert _get_code(u + "/v1/jobs", token="wrong") == 401
        assert _get_code(u + "/v1/jobs", token="s3cret") == 200
        # the WWW-Authenticate challenge names the scheme
        try:
            urllib.request.urlopen(u + "/v1/jobs", timeout=10)
        except urllib.error.HTTPError as e:
            assert e.headers.get("WWW-Authenticate") == "Bearer"
        # probes and scrapers stay open: not part of the routed surface
        assert _get_code(u + "/metrics") == 200
        assert _get_code(u + "/healthz") == 200
        # mutating routes are gated too
        req = urllib.request.Request(
            u + "/v1/drain", data=b"", method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                code = r.status
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 401

    # the env var is the deployment path; False force-disables
    monkeypatch.setenv("IGG_API_TOKEN", "envtok")
    with JobApiServer(d) as api:
        u = f"http://{api.host}:{api.port}"
        assert _get_code(u + "/v1/jobs") == 401
        assert _get_code(u + "/v1/jobs", token="envtok") == 200
    with JobApiServer(d, api_token=False) as api:
        assert _get_code(f"http://{api.host}:{api.port}/v1/jobs") == 200
    monkeypatch.delenv("IGG_API_TOKEN", raising=False)

    # the read-side planes take the same token
    with ObserveServer(d, api_token="obs") as obs:
        u = f"http://{obs.host}:{obs.port}"
        assert _get_code(u + "/v1/observe") == 401
        assert _get_code(u + "/v1/observe", token="obs") == 200
    root = tmp_path / "snaps"
    root.mkdir()
    with SnapshotQueryServer(str(root), api_token="q") as q:
        u = f"http://{q.host}:{q.port}"
        assert _get_code(u + "/v1/snapshots") == 401
        assert _get_code(u + "/v1/snapshots", token="q") == 200

    # an empty token is a misconfiguration, not an open server
    with pytest.raises(InvalidArgumentError, match="token"):
        JobApiServer(d, api_token="")
