"""Stochastic-rounding bf16 storage (`ops/precision.py`): the primitive's
statistical contract and the end-to-end accuracy claim — SR storage must
remove the increment-absorption stagnation that plain bf16 suffers on
long diffusion runs (measured in `bench_f64_accuracy.py`; the capability
the reference's Float32/Float64-only tiers cannot express)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion


def test_stochastic_round_unbiased():
    import jax
    import jax.numpy as jnp

    # 1 + 2^-9 sits 1/4 of the way between the bf16 neighbors 1.0 and
    # 1.0078125 (ulp at 1.0 is 2^-7): E[SR] = x, P(round up) = 1/4
    x = jnp.full((8192,), 1.0 + 2 ** -9, jnp.float32)
    outs = jnp.stack([
        igg.stochastic_round_bf16(x, jax.random.PRNGKey(i)).astype(
            jnp.float32) for i in range(8)])
    assert abs(float(outs.mean()) - (1.0 + 2 ** -9)) < 2e-4
    up = float((outs > 1.004).mean())
    assert 0.22 < up < 0.28
    # the set of produced values is exactly the two neighbors
    assert set(np.unique(np.asarray(outs))) == {1.0, 1.0078125}


def test_stochastic_round_exact_and_signs():
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    # exactly-representable values never move, either sign
    x = jnp.asarray([1.0, -1.0, 0.0, 0.5, -2.25], jnp.float32)
    out = igg.stochastic_round_bf16(x, key)
    assert np.array_equal(np.asarray(out, np.float32), np.asarray(x))
    # negative midpoint rounds between ITS neighbors (sign-magnitude trick)
    xm = jnp.full((4096,), -(1.0 + 2 ** -8), jnp.float32)  # halfway
    om = igg.stochastic_round_bf16(xm, key).astype(jnp.float32)
    assert set(np.unique(np.asarray(om))) == {-1.0078125, -1.0}
    assert abs(float(om.mean()) + (1.0 + 2 ** -8)) < 3e-4
    # non-finite inputs pass through
    bad = jnp.asarray([np.inf, -np.inf, np.nan], jnp.float32)
    ob = np.asarray(igg.stochastic_round_bf16(bad, key), np.float32)
    assert ob[0] == np.inf and ob[1] == -np.inf and np.isnan(ob[2])


def _final(dtype, sr, nt=200, seed=0):
    import jax.numpy as jnp

    igg.init_global_grid(24, 24, 24, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=dtype, sr=sr, sr_seed=seed)
        out = run_diffusion(T, Cp, p, nt, nt_chunk=50,
                            impl="xla" if not sr else None)
        return np.asarray(igg.gather_interior(out)).astype(np.float64)
    finally:
        igg.finalize_global_grid()


def test_sr_storage_fixes_bf16_stagnation():
    import jax.numpy as jnp

    ref = _final(np.float32, sr=False)
    plain = _final(jnp.bfloat16, sr=False)
    srd = _final(jnp.bfloat16, sr=True)
    scale = np.abs(ref).max()
    err_plain = np.abs(plain - ref).max() / scale
    err_sr = np.abs(srd - ref).max() / scale
    # plain bf16 stagnates (large deterministic bias); SR tracks the f32
    # trajectory to ~1e-2 — ~36x better at this 24³/200-step spot check,
    # ~86x (0.848 -> 0.0098) at the bench_f64_accuracy.py config
    # (F64_ACCURACY.json); the assertion keeps slack for RNG variation
    assert err_plain > 0.1
    assert err_sr < 0.05
    assert err_sr < err_plain / 5


def test_sr_requires_sr_runner():
    """make_run/make_step cannot thread the per-step PRNG: driving an
    sr=True bf16 state through them must raise, not silently run plain
    round-to-nearest (the stagnation sr exists to prevent)."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.models import make_run
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(24, 24, 24, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=jnp.bfloat16, sr=True)
        with pytest.raises(InvalidArgumentError):
            make_run(p, 2, impl="xla")(T, Cp)
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 2, impl="pallas_interpret")
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
def test_sr_deterministic_per_seed():
    """slow (tier-1 budget, ISSUE 8 trim): two extra 40-step SR runs
    (~10 s); the SR behaviors keep fast tier-1 coverage via the
    unbiasedness/exactness unit tests and the stagnation-fix run above."""
    import jax.numpy as jnp

    a = _final(jnp.bfloat16, sr=True, nt=40, seed=7)
    b = _final(jnp.bfloat16, sr=True, nt=40, seed=7)
    c = _final(jnp.bfloat16, sr=True, nt=40, seed=8)
    assert np.array_equal(a, b)       # same seed -> same trajectory
    assert not np.array_equal(a, c)   # the rounding is actually stochastic


# ---------------------------------------------------------------------------
# Quantized wire codec (`ops/precision.py`: WirePolicy + per-slab int8/int4)
# ---------------------------------------------------------------------------

quant = pytest.mark.quant


@quant
def test_wire_policy_parsing_and_roundtrip():
    from implicitglobalgrid_tpu.ops.precision import (
        WireFormat, WirePolicy, resolve_wire_dtype, wire_format_for,
    )
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    # uniform spellings (strings and dtypes) and the off forms
    assert resolve_wire_dtype("off") is None
    assert resolve_wire_dtype("") is None
    p8 = resolve_wire_dtype("int8")
    assert isinstance(p8, WirePolicy) and str(p8) == "int8"
    assert all(f == WireFormat("int8") for f in p8.per_dim)
    assert str(resolve_wire_dtype(np.float16)) == "float16"
    # per-axis syntax: x/y/z and gx/gy/gz both address dims; unnamed
    # axes stay exact; the canonical string round-trips
    pm = resolve_wire_dtype("z:int8,x:f32")
    assert pm.for_dim(2) == WireFormat("int8")
    assert pm.for_dim(0) == WireFormat("float32")
    assert pm.for_dim(1) is None
    assert str(resolve_wire_dtype(str(pm))) == str(pm) == "x:float32,z:int8"
    assert str(resolve_wire_dtype("gz:int4")) == "z:int4"
    assert str(resolve_wire_dtype({"z": "int8"})) == "z:int8"
    # errors: unknown format, unknown axis, duplicate axis, bare token
    for bad in ("int3", "z:int3", "w:int8", "z:int8,gz:int4", "z:int8,f32"):
        with pytest.raises(InvalidArgumentError):
            resolve_wire_dtype(bad)
    # narrowing rules: quant applies to every real float; casts must
    # strictly narrow; non-floats never convert
    assert wire_format_for(np.float32, pm, 2) == WireFormat("int8")
    assert wire_format_for(np.float32, pm, 0) is None   # f32 cast: no-op
    assert wire_format_for(np.float64, pm, 0) == WireFormat("float32")
    assert wire_format_for(np.int32, p8, 2) is None
    assert wire_format_for(np.float16, p8, 0) == WireFormat("int8")


@quant
def test_quantize_slab_constant_exact_and_bounded():
    """Per-slab max-abs scaling: a constant slab round-trips EXACTLY
    (q hits +/-L and dequant computes (q/L)*scale = +/-scale), an
    arbitrary slab stays within scale/(2L) of the source, and all-zero
    slabs dequantize to exact zeros (scale 1 guard, no 0/0)."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops.precision import (
        WireFormat, dequantize_slab, quant_slab_bytes, quantize_slab,
    )

    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.standard_normal(513) * 3.7, jnp.float32)
    for name, L in (("int8", 127), ("int4", 7)):
        fmt = WireFormat(name)
        q, s = quantize_slab(x, fmt)
        assert q.dtype == jnp.int8 and q.size == quant_slab_bytes(513, fmt)
        assert float(s[0]) == float(jnp.max(jnp.abs(x)))
        y = dequantize_slab(q, s, 513, fmt, jnp.float32)
        assert float(jnp.max(jnp.abs(y - x))) <= float(s[0]) / (2 * L) * 1.001
        # constant slabs (either sign) are exact
        for c in (2.7182817, -0.3333333):
            cx = jnp.full((9,), c, jnp.float32)
            cq, cs = quantize_slab(cx, fmt)
            assert np.array_equal(
                np.asarray(dequantize_slab(cq, cs, 9, fmt, jnp.float32)),
                np.asarray(cx))
        zq, zs = quantize_slab(jnp.zeros((4,), jnp.float32), fmt)
        assert float(zs[0]) == 1.0
        assert np.all(np.asarray(
            dequantize_slab(zq, zs, 4, fmt, jnp.float32)) == 0.0)


@quant
def test_quantize_slab_nonfinite_poisons_slab():
    """NaN/Inf propagation: any non-finite element poisons the SLAB's
    scale to NaN, so the dequantized halo is wholly non-finite — a NaN
    can coarsen to slab granularity but can never be laundered into a
    plausible finite value (the health guard still trips)."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops.precision import (
        WireFormat, dequantize_slab, quantize_slab,
    )

    for fmt in (WireFormat("int8"), WireFormat("int4")):
        for poison in (np.nan, np.inf, -np.inf):
            x = jnp.asarray([1.0, poison, -2.0, 0.5], jnp.float32)
            q, s = quantize_slab(x, fmt)
            assert np.isnan(float(s[0]))
            y = np.asarray(dequantize_slab(q, s, 4, fmt, jnp.float32))
            assert not np.isfinite(y).any()
    # DELIBERATE: finite f64 magnitudes beyond f32 range poison too —
    # the wire scale is f32, so the slab is unrepresentable; poisoning
    # fails loudly at the guard where a clamped scale would ship halos
    # wrong by orders of magnitude (see the quantize_slab docstring)
    big = jnp.asarray([1e300, 1.0], jnp.float64)
    q, s = quantize_slab(big, WireFormat("int8"))
    assert np.isnan(float(s[0]))
    y = np.asarray(dequantize_slab(q, s, 2, WireFormat("int8"), jnp.float64))
    assert not np.isfinite(y).any()


@quant
def test_int4_pack_unpack_parity_with_int8():
    """Bit-packed int4 is int8 with 4-bit levels, not a different codec:
    on values int4 represents exactly (multiples of scale/7) the two
    formats agree bit-for-bit, and odd-length slabs survive the pad
    nibble."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops.precision import (
        WireFormat, dequantize_slab, quantize_slab,
    )

    from implicitglobalgrid_tpu.ops.precision import (
        _pack_int4, _unpack_int4,
    )

    f8, f4 = WireFormat("int8"), WireFormat("int4")
    # the nibble pack is a pure bijection on [-7, 7], odd lengths padded
    for n in (7, 8):
        q = jnp.asarray(np.arange(n) % 15 - 7, jnp.int8)
        packed = _pack_int4(q)
        assert packed.size == (n + 1) // 2
        assert np.array_equal(np.asarray(_unpack_int4(packed, n)),
                              np.asarray(q))
    # 7 exactly-representable levels incl. both extremes, odd length:
    # int4 round-trips them bit-exactly, int8 agrees wherever ITS levels
    # are exact too (the two formats share one codec, only L differs)
    x = jnp.asarray([7, -7, 3, 0, -1, 5, -4], jnp.float32) / 7 * 2.5
    q8, s8_ = quantize_slab(x, f8)
    q4, s4_ = quantize_slab(x, f4)
    assert float(s8_[0]) == float(s4_[0]) == 2.5
    assert q4.size == 4 and q8.size == 7  # (7+1)//2 packed bytes
    y8 = np.asarray(dequantize_slab(q8, s8_, 7, f8, jnp.float32))
    y4 = np.asarray(dequantize_slab(q4, s4_, 7, f4, jnp.float32))
    assert np.array_equal(y4, np.asarray(x))  # exact levels round-trip
    assert np.abs(y8 - np.asarray(x)).max() <= 2.5 / (2 * 127) * 1.001
    assert np.array_equal(y8[[0, 1, 3]], y4[[0, 1, 3]])  # shared levels


@quant
def test_scales_codec_roundtrip():
    """The per-slab f32 scales ride the int8 buffer bitcast: bit-exact
    round-trip, NaN included (the poison marker must survive the wire)."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops.precision import (
        SCALE_BYTES, decode_scales, encode_scales,
    )

    vals = [1.5, np.pi, 1e-30, np.nan]
    scales = [jnp.asarray([v], jnp.float32) for v in vals]
    buf = encode_scales(scales)
    assert buf.dtype == jnp.int8 and buf.size == SCALE_BYTES * len(vals)
    dec = np.asarray(decode_scales(buf, len(vals)))
    ref = np.asarray(vals, np.float32)
    assert np.array_equal(dec.view(np.uint32), ref.view(np.uint32))
