"""Stochastic-rounding bf16 storage (`ops/precision.py`): the primitive's
statistical contract and the end-to-end accuracy claim — SR storage must
remove the increment-absorption stagnation that plain bf16 suffers on
long diffusion runs (measured in `bench_f64_accuracy.py`; the capability
the reference's Float32/Float64-only tiers cannot express)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion


def test_stochastic_round_unbiased():
    import jax
    import jax.numpy as jnp

    # 1 + 2^-9 sits 1/4 of the way between the bf16 neighbors 1.0 and
    # 1.0078125 (ulp at 1.0 is 2^-7): E[SR] = x, P(round up) = 1/4
    x = jnp.full((8192,), 1.0 + 2 ** -9, jnp.float32)
    outs = jnp.stack([
        igg.stochastic_round_bf16(x, jax.random.PRNGKey(i)).astype(
            jnp.float32) for i in range(8)])
    assert abs(float(outs.mean()) - (1.0 + 2 ** -9)) < 2e-4
    up = float((outs > 1.004).mean())
    assert 0.22 < up < 0.28
    # the set of produced values is exactly the two neighbors
    assert set(np.unique(np.asarray(outs))) == {1.0, 1.0078125}


def test_stochastic_round_exact_and_signs():
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    # exactly-representable values never move, either sign
    x = jnp.asarray([1.0, -1.0, 0.0, 0.5, -2.25], jnp.float32)
    out = igg.stochastic_round_bf16(x, key)
    assert np.array_equal(np.asarray(out, np.float32), np.asarray(x))
    # negative midpoint rounds between ITS neighbors (sign-magnitude trick)
    xm = jnp.full((4096,), -(1.0 + 2 ** -8), jnp.float32)  # halfway
    om = igg.stochastic_round_bf16(xm, key).astype(jnp.float32)
    assert set(np.unique(np.asarray(om))) == {-1.0078125, -1.0}
    assert abs(float(om.mean()) + (1.0 + 2 ** -8)) < 3e-4
    # non-finite inputs pass through
    bad = jnp.asarray([np.inf, -np.inf, np.nan], jnp.float32)
    ob = np.asarray(igg.stochastic_round_bf16(bad, key), np.float32)
    assert ob[0] == np.inf and ob[1] == -np.inf and np.isnan(ob[2])


def _final(dtype, sr, nt=200, seed=0):
    import jax.numpy as jnp

    igg.init_global_grid(24, 24, 24, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=dtype, sr=sr, sr_seed=seed)
        out = run_diffusion(T, Cp, p, nt, nt_chunk=50,
                            impl="xla" if not sr else None)
        return np.asarray(igg.gather_interior(out)).astype(np.float64)
    finally:
        igg.finalize_global_grid()


def test_sr_storage_fixes_bf16_stagnation():
    import jax.numpy as jnp

    ref = _final(np.float32, sr=False)
    plain = _final(jnp.bfloat16, sr=False)
    srd = _final(jnp.bfloat16, sr=True)
    scale = np.abs(ref).max()
    err_plain = np.abs(plain - ref).max() / scale
    err_sr = np.abs(srd - ref).max() / scale
    # plain bf16 stagnates (large deterministic bias); SR tracks the f32
    # trajectory to ~1e-2 — ~36x better at this 24³/200-step spot check,
    # ~86x (0.848 -> 0.0098) at the bench_f64_accuracy.py config
    # (F64_ACCURACY.json); the assertion keeps slack for RNG variation
    assert err_plain > 0.1
    assert err_sr < 0.05
    assert err_sr < err_plain / 5


def test_sr_requires_sr_runner():
    """make_run/make_step cannot thread the per-step PRNG: driving an
    sr=True bf16 state through them must raise, not silently run plain
    round-to-nearest (the stagnation sr exists to prevent)."""
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.models import make_run
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(24, 24, 24, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=jnp.bfloat16, sr=True)
        with pytest.raises(InvalidArgumentError):
            make_run(p, 2, impl="xla")(T, Cp)
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 2, impl="pallas_interpret")
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
def test_sr_deterministic_per_seed():
    """slow (tier-1 budget, ISSUE 8 trim): two extra 40-step SR runs
    (~10 s); the SR behaviors keep fast tier-1 coverage via the
    unbiasedness/exactness unit tests and the stagnation-fix run above."""
    import jax.numpy as jnp

    a = _final(jnp.bfloat16, sr=True, nt=40, seed=7)
    b = _final(jnp.bfloat16, sr=True, nt=40, seed=7)
    c = _final(jnp.bfloat16, sr=True, nt=40, seed=8)
    assert np.array_equal(a, b)       # same seed -> same trajectory
    assert not np.array_equal(a, c)   # the rounding is actually stochastic
