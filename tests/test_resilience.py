"""Resilient-runtime tests: the supervised driver loop with every recovery
path driven by DETERMINISTIC fault injection (`runtime/faults.py`) — the
acceptance bar is bit-identical final state vs an uninterrupted reference
run, not 'the run survived'."""

import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import (
    InvalidArgumentError, ResilienceError,
)

from conftest import (
    health_counters_from_registry as _health_counters,
    reset_health_counters_in_registry as _reset_health_counters,
)


def _init(dimx=2, dimy=2, dimz=1):
    igg.init_global_grid(6, 6, 6, dimx=dimx, dimy=dimy, dimz=dimz,
                         quiet=True)


def _diffusion_step():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float64)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


_REF_CACHE: dict = {}


def _reference_run(tmp_path, nt=20, nt_chunk=5):
    """Uninterrupted reference: same driver, no faults; returns the
    gathered interior (decomposition-independent comparison target).
    Memoized — the fault-matrix tests all compare against the same run."""
    key = (nt, nt_chunk)
    if key in _REF_CACHE:
        return _REF_CACHE[key]
    _init()
    step, state = _diffusion_step()
    ref, reports = igg.run_resilient(
        step, state, nt, nt_chunk=nt_chunk, key="resil_ref",
        checkpoint_dir=str(tmp_path / "ck_ref"))
    assert all(r.ok for r in reports)
    P = igg.gather_interior(ref["T"])
    igg.finalize_global_grid()
    _REF_CACHE[key] = P
    return P


# ---------------------------------------------------------------------------
# Public API completeness (satellite: the runtime API is exported top-level)
# ---------------------------------------------------------------------------

def test_public_api_exports():
    for sym in ("run_resilient", "HealthReport", "GuardConfig",
                "RecoveryPolicy", "NaNPoke", "CheckpointCorruption",
                "ProcessLoss", "poke_nan", "corrupt_checkpoint",
                "elastic_restart", "restore_checkpoint_elastic",
                "saved_topology", "elastic_local_size"):
        assert hasattr(igg, sym), sym
        assert sym in igg.__all__, sym
    # the PR-2 health-counter shims are RETIRED (two majors of notice):
    # the igg_health_events_total registry family is the only API
    for gone in ("health_counters", "record_health_event",
                 "reset_health_counters"):
        assert not hasattr(igg, gone), gone
        assert gone not in igg.__all__, gone


def test_public_api_importable_in_subprocess():
    """The satellite's literal check: a fresh interpreter can import the
    package and resolve the runtime entry point (catches import cycles
    that an already-imported test session would mask)."""
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import implicitglobalgrid_tpu as igg; igg.run_resilient"],
        capture_output=True, text=True, timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]


# ---------------------------------------------------------------------------
# Healthy-path semantics
# ---------------------------------------------------------------------------

def test_unsupervised_equivalence_and_reports(tmp_path):
    """With no faults, run_resilient is exactly the chunked runner plus
    reports: same trajectory as run_diffusion, one report per chunk."""
    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    _init()
    step, state = _diffusion_step()
    out, reports = igg.run_resilient(step, state, 15, nt_chunk=5,
                                     key="resil_eq")
    T0, Cp, p = init_diffusion3d(dtype=np.float64)
    T_ref = run_diffusion(T0, Cp, p, 15, nt_chunk=5)
    assert np.array_equal(np.asarray(out["T"]), np.asarray(T_ref))
    assert len(reports) == 3 and all(r.ok for r in reports)
    assert [r.step_begin for r in reports] == [0, 5, 10]
    assert reports[-1].step_end == 15
    assert all(r.nonfinite == {"T": 0, "Cp": 0} for r in reports)
    assert all(r.rms["T"] > 0 for r in reports)


@pytest.mark.slow
def test_health_counters_record_and_reset(tmp_path):
    """Full-run counter sweep (slow: one extra supervised run+compile).
    The fast tier keeps the registry-family contract in
    test_telemetry.py::test_health_events_family_in_registry and the
    per-path counter asserts inside the fault-matrix tests."""
    _reset_health_counters()
    _init()
    step, state = _diffusion_step()
    igg.run_resilient(step, state, 10, nt_chunk=5, key="resil_cnt",
                      checkpoint_dir=str(tmp_path / "ck"))
    c = _health_counters()
    assert c["chunks"] == 2
    assert c["checkpoints_saved"] == 3  # initial + one per chunk boundary
    assert "guard_trips" not in c
    _reset_health_counters()
    assert _health_counters() == {}


def test_terminal_checkpoint_saved_off_cadence(tmp_path):
    """nt % checkpoint_every != 0 must still save the TERMINAL state, so a
    follow-on run can resume from step nt instead of replaying from the
    last cadence save (satellite of ISSUE 3)."""
    from implicitglobalgrid_tpu.runtime.driver import _CheckpointSlots

    _init()
    step, state = _diffusion_step()
    out, reports = igg.run_resilient(
        step, state, 12, nt_chunk=5, key="resil_final",
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=5)
    st, at, fellback = _CheckpointSlots(str(tmp_path / "ck")).restore()
    assert at == 12 and not fellback
    assert np.array_equal(np.asarray(st["T"]), np.asarray(out["T"]))


@pytest.mark.slow
def test_terminal_checkpoint_on_cadence_single_save(tmp_path):
    """On-cadence end: exactly one save at the final step, not two (the
    complement of the off-cadence regression above; slow: a second full
    run+compile for one counter assert)."""
    from implicitglobalgrid_tpu.runtime.driver import _CheckpointSlots

    _init()
    step, state = _diffusion_step()
    _reset_health_counters()
    out, reports = igg.run_resilient(
        step, dict(state), 10, nt_chunk=5, key="resil_final2",
        checkpoint_dir=str(tmp_path / "ck2"), checkpoint_every=5)
    assert _health_counters()["checkpoints_saved"] == 3  # init + 5 + 10
    st, at, _ = _CheckpointSlots(str(tmp_path / "ck2")).restore()
    assert at == 10


def test_guard_trip_without_checkpoint_is_fatal():
    _init()
    step, state = _diffusion_step()
    state["T"] = igg.poke_nan(state["T"], (0, 0, 0))
    with pytest.raises(ResilienceError, match="nonfinite:T"):
        igg.run_resilient(step, state, 10, nt_chunk=5, key="resil_fatal")


def test_rms_guard_trips():
    """Field-norm divergence guard: a healthy state over a tiny rms_limit
    must trip with the rms reason (per-field dict limits honored)."""
    _init()
    step, state = _diffusion_step()
    with pytest.raises(ResilienceError, match="rms:T"):
        igg.run_resilient(step, state, 10, nt_chunk=5, key="resil_rms",
                          guard=igg.GuardConfig(rms_limit={"T": 1e-30}))


def test_state_validation():
    _init()
    step, state = _diffusion_step()
    with pytest.raises(InvalidArgumentError, match="non-empty dict"):
        igg.run_resilient(step, (state["T"],), 10)
    with pytest.raises(InvalidArgumentError, match="unknown field"):
        igg.run_resilient(step, state, 10,
                          faults=[igg.NaNPoke(step=1, name="nope")])
    with pytest.raises(InvalidArgumentError, match="step range"):
        igg.run_resilient(step, state, 10,
                          faults=[igg.NaNPoke(step=99, name="T")])
    # a typo'd lint rule must fail FAST at call time — inside the chunk
    # loop it would only surface as a buried `audit_failed` event,
    # silently disabling the audit the caller explicitly opted into
    with pytest.raises(InvalidArgumentError, match="needs audit=True"):
        igg.run_resilient(step, state, 10,
                          audit_lints=("host-transfer",))
    with pytest.raises(InvalidArgumentError, match="unknown lint rule"):
        igg.run_resilient(step, state, 10, audit=True,
                          audit_lints=("host-transfr",))


# ---------------------------------------------------------------------------
# The fault-injection matrix (tier-1: every recovery path exercised)
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_nan_injection_rollback_bit_identical(tmp_path):
    """THE acceptance loop: inject NaN at step 12 → guard trips within one
    chunk → rollback to last-good → run completes bit-identical to the
    uninterrupted reference."""
    P_ref = _reference_run(tmp_path)

    _init()
    _reset_health_counters()
    step, state = _diffusion_step()
    out, reports = igg.run_resilient(
        step, state, 20, nt_chunk=5, key="resil_nan",
        checkpoint_dir=str(tmp_path / "ck"),
        faults=[igg.NaNPoke(step=12, name="T", index=(0, 0, 0))])

    tripped = [r for r in reports if not r.ok]
    assert len(tripped) == 1
    # the chunk schedule split at the injection step and the guard tripped
    # within that one chunk
    assert tripped[0].step_begin == 12 and tripped[0].step_end <= 17
    assert tripped[0].reasons == ("nonfinite:T",)
    assert tripped[0].nonfinite["T"] > 0
    c = _health_counters()
    assert c["guard_trips"] == 1 and c["rollbacks"] == 1
    assert np.array_equal(igg.gather_interior(out["T"]), P_ref)


@pytest.mark.faults
def test_process_loss_elastic_restart_identical(tmp_path):
    """Simulated process loss at step 13: state abandoned, grid re-inited
    with dims=(1,2,2), last-good checkpoint redistributed elastically,
    lost steps recomputed — final interior identical to the reference run
    on the ORIGINAL decomposition. With ``audit=True`` every DISTINCT
    chunk program dispatched is audited once: the steady n=5 runner, the
    fault-split n=3 runner, and — after the restart — the rebuilt
    decomposition's n=5 runner again (three ``audit`` events, all
    clean)."""
    P_ref = _reference_run(tmp_path)

    _init()
    _reset_health_counters()
    step, state = _diffusion_step()
    igg.start_flight_recorder(str(tmp_path / "fr.jsonl"))
    try:
        out, reports = igg.run_resilient(
            step, state, 20, nt_chunk=5, key="resil_loss",
            checkpoint_dir=str(tmp_path / "ck"), audit=True,
            faults=[igg.ProcessLoss(step=13, new_dims=(1, 2, 2))])
    finally:
        igg.stop_flight_recorder()

    gg = igg.global_grid()
    assert tuple(int(d) for d in gg.dims) == (1, 2, 2)  # run ended elastic
    c = _health_counters()
    assert c["elastic_restarts"] == 1
    assert np.array_equal(igg.gather_interior(out["T"]), P_ref)
    audits = [e for e in igg.read_flight_events(str(tmp_path / "fr.jsonl"))
              if e.get("kind") == "audit"]
    assert len(audits) == 3 and all(a["ok"] for a in audits)


@pytest.mark.faults
@pytest.mark.slow
def test_nan_after_elastic_restart_rolls_back_on_new_grid(tmp_path):
    """Compound failure: process loss at 13 (elastic restart to (1,2,2)),
    then SDC at 14 — the rollback after the restart must restore onto the
    NEW decomposition (the driver re-anchors its slots right after the
    elastic restore) and the run still end identical to the reference."""
    P_ref = _reference_run(tmp_path)

    _init()
    _reset_health_counters()
    step, state = _diffusion_step()
    out, reports = igg.run_resilient(
        step, state, 20, nt_chunk=5, key="resil_combo",
        checkpoint_dir=str(tmp_path / "ck"),
        faults=[igg.ProcessLoss(step=13, new_dims=(1, 2, 2)),
                igg.NaNPoke(step=14, name="T")])
    c = _health_counters()
    assert c["elastic_restarts"] == 1
    assert c["guard_trips"] == 1 and c["rollbacks"] == 1
    assert np.array_equal(igg.gather_interior(out["T"]), P_ref)


@pytest.mark.faults
def test_checkpoint_corruption_falls_back_to_other_slot(tmp_path):
    """Storage fault: the newest checkpoint is bit-flipped after its save;
    the later rollback must DETECT it (content checksum) and fall back to
    the other (older) slot, recompute, and still match the reference."""
    P_ref = _reference_run(tmp_path)

    _init()
    _reset_health_counters()
    step, state = _diffusion_step()
    out, reports = igg.run_resilient(
        step, state, 20, nt_chunk=5, key="resil_corrupt",
        checkpoint_dir=str(tmp_path / "ck"),
        faults=[igg.CheckpointCorruption(save_index=2, kind="bitflip"),
                igg.NaNPoke(step=12, name="T")])
    c = _health_counters()
    assert c["rollbacks"] == 1 and c["restore_fallbacks"] == 1
    assert np.array_equal(igg.gather_interior(out["T"]), P_ref)


@pytest.mark.faults
@pytest.mark.parametrize("kind,target", [
    # one fast representative of the both-slots-fatal path; the other
    # corruption flavors ride the slow tier (identical driver path,
    # different blockio damage — each is a full faulted run+compile)
    pytest.param("truncate", "shard", marks=pytest.mark.slow),
    ("delete", "shard"),
    pytest.param("bitflip", "meta", marks=pytest.mark.slow),
])
def test_corruption_matrix_both_slots_fatal(tmp_path, kind, target):
    """Corrupting EVERY slot (here: the only save) must end in a clean
    typed failure, never a garbage restore."""
    _init()
    step, state = _diffusion_step()
    with pytest.raises(ResilienceError, match="No checkpoint slot"):
        igg.run_resilient(
            step, state, 10, nt_chunk=5, key=("resil_cm", kind, target),
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=100,
            faults=[igg.CheckpointCorruption(save_index=0, kind=kind,
                                             target=target),
                    igg.NaNPoke(step=7, name="T")])


@pytest.mark.faults
def test_persistent_failure_escalates_then_exhausts(tmp_path):
    """A fault rollback cannot cure (the step function itself poisons the
    state) must shrink the chunk (escalation hook called), then exhaust
    the bounded retry budget with a typed error — no infinite loop."""
    _init()
    step, state = _diffusion_step()

    def poisoned(s):
        out = step(s)
        return {"T": out["T"].at[0, 0, 0].set(float("nan")),
                "Cp": out["Cp"]}

    _reset_health_counters()
    seen = []
    with pytest.raises(ResilienceError, match="retry budget"):
        igg.run_resilient(
            poisoned, state, 20, nt_chunk=8, key="resil_poison",
            checkpoint_dir=str(tmp_path / "ck"),
            policy=igg.RecoveryPolicy(max_retries=3, shrink_chunk_after=2,
                                      on_escalate=seen.append))
    c = _health_counters()
    assert c["guard_trips"] == 4  # max_retries + the final fatal trip
    assert c["escalations"] >= 1
    assert seen and seen[0]["nt_chunk"] < 8  # hook saw the shrunk chunk


@pytest.mark.faults
def test_elastic_restart_requires_checkpoint_dir():
    _init()
    step, state = _diffusion_step()
    with pytest.raises(ResilienceError, match="no checkpoint_dir"):
        igg.run_resilient(step, state, 10, nt_chunk=5, key="resil_nockpt",
                          faults=[igg.ProcessLoss(step=5,
                                                  new_dims=(1, 2, 2))])


# ---------------------------------------------------------------------------
# Ensemble axis (ISSUE 12): per-member fault isolation
# ---------------------------------------------------------------------------

def _ensemble_setup(E):
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, ensemble_state, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float64)
    state = {"T": ensemble_state(T, E, perturb=0.01),
             "Cp": ensemble_state(Cp, E)}

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, state


@pytest.mark.faults
@pytest.mark.ensemble
def test_ensemble_member_fault_isolated_rollback(tmp_path):
    """THE per-member isolation claim: NaN poked into member 2 of an E=4
    batch (NaNPoke's index leads with the member axis) trips the guard
    for THAT member alone, the driver pins the healthy members' committed
    output and replays from the last-good save (``member_rollback`` then
    ``member_splice`` events, ``member_rollbacks`` counter), and the
    final batch — survivors AND the healed member, whose poke was a
    one-shot fault — is bit-identical to the unfaulted ensemble
    reference. The E x policy matrix rides the slow tier below."""
    E = 4
    _init()
    step, state = _ensemble_setup(E)
    ref, ref_reports = igg.run_resilient(
        step, state, 12, nt_chunk=3, key="ens_resil", ensemble=E,
        checkpoint_dir=str(tmp_path / "ck_ref"))
    assert len(ref_reports) == 4 * E  # one report per (chunk, member)
    assert all(r.ok for r in ref_reports)
    assert {r.member for r in ref_reports} == set(range(E))

    # same grid, same runner key: the faulted run replays warm from the
    # same compiled chunk (state arrays are immutable — reuse them)
    _reset_health_counters()
    igg.start_flight_recorder(str(tmp_path / "fr.jsonl"))
    try:
        out, reports = igg.run_resilient(
            step, state, 12, nt_chunk=3, key="ens_resil", ensemble=E,
            checkpoint_dir=str(tmp_path / "ck"),
            faults=[igg.NaNPoke(step=6, name="T", index=(2, 0, 0, 0))])
    finally:
        igg.stop_flight_recorder()

    tripped = [r for r in reports if not r.ok]
    assert [r.member for r in tripped] == [2]
    assert tripped[0].reasons == ("nonfinite:T",)
    assert tripped[0].step_begin == 6
    c = _health_counters()
    assert c["guard_trips"] == 1 and c["rollbacks"] == 1
    assert c["member_rollbacks"] == 1
    evs = igg.read_flight_events(str(tmp_path / "fr.jsonl"))
    mr = [e for e in evs if e.get("kind") == "member_rollback"]
    ms = [e for e in evs if e.get("kind") == "member_splice"]
    assert len(mr) == 1 and mr[0]["members"] == [2] \
        and sorted(mr[0]["pinned"]) == [0, 1, 3]
    assert len(ms) == 1 and sorted(ms[0]["members"]) == [0, 1, 3]
    # survivors (and the healed member) end bit-identical to the
    # unfaulted ensemble run — which test_ensemble.py pins member-by-
    # member to the solo trajectories
    assert np.array_equal(np.asarray(out["T"]), np.asarray(ref["T"]))
    # per-member trip attribution in the registry
    fam = igg.metrics_registry().get("igg_member_guard_trips_total")
    trips = {l["member"]: v for l, v in fam.samples()}
    assert trips == {"2": 1.0}


@pytest.mark.faults
@pytest.mark.ensemble
@pytest.mark.slow
def test_ensemble_two_members_tripped_same_chunk(tmp_path):
    """Two members poked in one boundary (1 and 5 of E=8): ONE trip event
    names both, the pin covers the other six, and the batch still ends
    identical to the unfaulted reference (slow: a second full E=8
    supervised pair — the fast E=4 single-member representative above is
    the tier-1 coverage)."""
    E = 8
    _init()
    step, state = _ensemble_setup(E)
    ref, _ = igg.run_resilient(
        step, state, 9, nt_chunk=3, key="ens_resil8", ensemble=E,
        checkpoint_dir=str(tmp_path / "ck_ref"))

    _reset_health_counters()
    out, reports = igg.run_resilient(
        step, state, 9, nt_chunk=3, key="ens_resil8", ensemble=E,
        checkpoint_dir=str(tmp_path / "ck"),
        faults=[igg.NaNPoke(step=3, name="T", index=(1, 0, 0, 0)),
                igg.NaNPoke(step=3, name="T", index=(5, 1, 1, 1))])
    tripped = [r for r in reports if not r.ok]
    assert sorted(r.member for r in tripped) == [1, 5]
    c = _health_counters()
    assert c["guard_trips"] == 1 and c["member_rollbacks"] == 1
    assert np.array_equal(np.asarray(out["T"]), np.asarray(ref["T"]))


# ---------------------------------------------------------------------------
# Fault primitives
# ---------------------------------------------------------------------------

def test_poke_nan_targets_one_cell():
    _init()
    T = igg.ones_g()
    T2 = igg.poke_nan(T, (3, 4, 5))
    h = np.asarray(T2)
    assert np.isnan(h[3, 4, 5]) and np.isfinite(np.delete(h.ravel(),
                                                          np.ravel_multi_index((3, 4, 5), h.shape))).all()


def test_corrupt_checkpoint_validation(tmp_path):
    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()})
    with pytest.raises(InvalidArgumentError, match="kind"):
        igg.corrupt_checkpoint(d, kind="nope")
    with pytest.raises(InvalidArgumentError, match="target"):
        igg.corrupt_checkpoint(d, target="nope")
    with pytest.raises(InvalidArgumentError, match="no such"):
        igg.corrupt_checkpoint(str(tmp_path / "missing"))
