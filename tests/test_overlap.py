"""Tests of `hide_communication` — the overlapped step must be semantically
identical to plain update-then-exchange (the reference's `@hide_communication`
contract: same results, communication hidden; `reference README.md:10`)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d
from implicitglobalgrid_tpu.ops.overlap import hide_communication
from implicitglobalgrid_tpu.utils.compat import shard_map
from implicitglobalgrid_tpu.ops.stencil import (
    d_xa, d_xi, d_ya, d_yi, d_za, d_zi, inn,
)


def assert_overlap_equal(a, b, steps=1):
    """hide_communication vs plain update-then-exchange.

    Bit-identical on the jax>=0.6 toolchain the repo targets — and
    asserted so there. The XLA:CPU pipeline of jax 0.4.x contracts the
    shell/interior recompute fusions differently inside the larger
    shard_map program, producing ulp-scale differences (the slab
    recompute in ISOLATION is bitwise equal to the full-block update —
    verified while triaging; the divergence appears only with the stitch
    fused in). Accept ulp-scale drift ONLY on that toolchain, so a real
    regression can never hide behind the tolerance on modern jax."""
    if np.array_equal(a, b):
        return
    if jax.__version_info__ >= (0, 6):
        np.testing.assert_array_equal(a, b)
        return
    eps = float(np.finfo(a.dtype).eps)
    tol = 64 * eps * steps
    np.testing.assert_allclose(a, b, rtol=tol,
                               atol=tol * max(1.0, float(np.abs(a).max())))


def _update(p):
    def f(T, Cp):
        qx = -p.lam * d_xi(T) / p.dx
        qy = -p.lam * d_yi(T) / p.dy
        qz = -p.lam * d_zi(T) / p.dz
        dT = (-d_xa(qx) / p.dx - d_ya(qy) / p.dy - d_za(qz) / p.dz) / inn(Cp)
        return T.at[1:-1, 1:-1, 1:-1].add(p.dt * dT)
    return f


def _compare(periods, dims, nx=12):
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    up = _update(p)
    spec = P("gx", "gy", "gz")

    plain = jax.jit(shard_map(
        lambda t, c: igg.local_update_halo(up(t, c)),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    overlapped = jax.jit(shard_map(
        lambda t, c: hide_communication(up, t, c, radius=1),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))

    a = np.asarray(plain(T, Cp))
    b = np.asarray(overlapped(T, Cp))
    igg.finalize_global_grid()
    return a, b


@pytest.mark.parametrize("periods,dims", [
    ((0, 0, 0), (2, 2, 2)),
    ((1, 1, 1), (2, 2, 2)),
    ((1, 0, 1), (4, 2, 1)),
    ((1, 1, 1), (1, 1, 1)),   # self-neighbor path
])
def test_overlapped_equals_plain(periods, dims):
    a, b = _compare(periods, dims)
    assert_overlap_equal(a, b)


def test_overlapped_multiple_steps():
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    up = _update(p)
    spec = P("gx", "gy", "gz")
    from jax import lax

    f = jax.jit(shard_map(
        lambda t, c: lax.fori_loop(
            0, 5, lambda i, tc: hide_communication(up, tc, c), t),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    g = jax.jit(shard_map(
        lambda t, c: lax.fori_loop(
            0, 5, lambda i, tc: igg.local_update_halo(up(tc, c)), t),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    assert_overlap_equal(np.asarray(f(T, Cp)), np.asarray(g(T, Cp)), steps=5)


def test_thin_block_fallback():
    # block too thin to split -> falls back to the plain path, same result
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    up = _update(p)
    spec = P("gx", "gy", "gz")
    a = np.asarray(jax.jit(shard_map(
        lambda t, c: hide_communication(up, t, c),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))(T, Cp))
    b = np.asarray(jax.jit(shard_map(
        lambda t, c: igg.local_update_halo(up(t, c)),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))(T, Cp))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("wire", ["z:int8", "z:int8,x:f32"])
def test_overlapped_equals_plain_quantized_wire(wire):
    """ISSUE 11 small fix: the overlapped path and the plain fallback must
    agree under QUANTIZED per-axis wire policies too (previously only the
    exact wire was asserted). Equality holds because the send slabs are
    extracted from the shell, whose values equal the plain update's — so
    the per-slab max-abs quantization scales cannot diverge between the
    paths; a shell that drifted by even one ulp would flip quantization
    bins and fail this test loudly."""
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    up = _update(p)
    spec = P("gx", "gy", "gz")

    plain = jax.jit(shard_map(
        lambda t, c: igg.local_update_halo(up(t, c), wire_dtype=wire),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    overlapped = jax.jit(shard_map(
        lambda t, c: hide_communication(up, t, c, radius=1,
                                        wire_dtype=wire),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    a = np.asarray(plain(T, Cp))
    b = np.asarray(overlapped(T, Cp))
    igg.finalize_global_grid()
    assert_overlap_equal(a, b)


def test_multi_field_overlap_staggered_equals_plain():
    """The MULTI-FIELD interior-first shape (`hide_communication` on a
    tuple of staggered outputs — the acoustic V round's form): one
    coalesced exchange round of all outputs, same values as plain
    update-then-exchange."""
    from implicitglobalgrid_tpu.models import init_acoustic3d

    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, quiet=True)
    gg = igg.global_grid()
    (Pf, Vx, Vy, Vz), p = init_acoustic3d(dtype=np.float64)
    from jax import lax

    def dP(A, d):
        n = A.shape[d]
        return (lax.slice_in_dim(A, 1, n, axis=d)
                - lax.slice_in_dim(A, 0, n - 1, axis=d))

    def v_upd(vx, vy, vz, Pc):
        vx = vx.at[1:-1, :, :].add(-p.dt / p.rho * dP(Pc, 0) / p.dx)
        vy = vy.at[:, 1:-1, :].add(-p.dt / p.rho * dP(Pc, 1) / p.dy)
        vz = vz.at[:, :, 1:-1].add(-p.dt / p.rho * dP(Pc, 2) / p.dz)
        return vx, vy, vz

    spec = P("gx", "gy", "gz")
    specs = (spec, spec, spec, spec)

    plain = jax.jit(shard_map(
        lambda vx, vy, vz, Pc: igg.local_update_halo(*v_upd(vx, vy, vz, Pc)),
        mesh=gg.mesh, in_specs=specs, out_specs=specs[:3]))
    overlapped = jax.jit(shard_map(
        lambda vx, vy, vz, Pc: hide_communication(
            v_upd, (vx, vy, vz), Pc, radius=1),
        mesh=gg.mesh, in_specs=specs, out_specs=specs[:3]))
    a = plain(Vx, Vy, Vz, Pf)
    b = overlapped(Vx, Vy, Vz, Pf)
    igg.finalize_global_grid()
    for x, y in zip(a, b):
        assert_overlap_equal(np.asarray(x), np.asarray(y))


def test_stokes_overlap_matches_plain():
    """StokesParams(overlap=True) routes the XLA PT iteration through the
    interior-first shape (7 shell updates, one coalesced 4-field round,
    interior under the collectives); results must match the plain path
    (bit-identical on the jax>=0.6 toolchain; ulp tolerance on 0.4.x —
    `assert_overlap_equal`, same caveat as the step's own docstring)."""
    import dataclasses

    from implicitglobalgrid_tpu.models import init_stokes3d, run_stokes

    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    a = run_stokes(state, p, 6, nt_chunk=3, impl="xla")
    po = dataclasses.replace(p, overlap=True)
    b = run_stokes(state, po, 6, nt_chunk=3, impl="xla")
    igg.finalize_global_grid()
    for x, y in zip(a, b):
        assert_overlap_equal(np.asarray(x), np.asarray(y), steps=6)


def test_diffusion_overlap_matches_plain():
    """DiffusionParams(overlap=True) routes the XLA step through
    hide_communication; results must equal the plain path bit-for-bit."""
    import dataclasses

    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    po = dataclasses.replace(p, overlap=True)
    a = np.asarray(igg.gather(run_diffusion(T, Cp, p, 6, nt_chunk=3,
                                            impl="xla")))
    b = np.asarray(igg.gather(run_diffusion(T, Cp, po, 6, nt_chunk=3,
                                            impl="xla")))
    assert_overlap_equal(a, b, steps=6)


def test_diffusion2d_overlap_matches_plain():
    import dataclasses

    from implicitglobalgrid_tpu.models import init_diffusion2d, run_diffusion

    igg.init_global_grid(8, 8, 1, dimx=2, dimy=2, periodx=1, quiet=True)
    T, Cp, p = init_diffusion2d(dtype=np.float32)
    po = dataclasses.replace(p, overlap=True)
    a = np.asarray(igg.gather(run_diffusion(T, Cp, p, 6, nt_chunk=3,
                                            impl="xla")))
    b = np.asarray(igg.gather(run_diffusion(T, Cp, po, 6, nt_chunk=3,
                                            impl="xla")))
    assert_overlap_equal(a, b, steps=6)
