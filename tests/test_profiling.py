"""Profiling helpers: trace capture produces artifacts, annotate nests."""

import os

import numpy as np

import implicitglobalgrid_tpu as igg


def test_trace_and_annotate(tmp_path):
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    A = igg.device_put_g(np.ones((8, 8, 8), np.float32))
    with igg.trace(str(tmp_path)):
        with igg.annotate("halo"):
            A = igg.update_halo(A)
        igg.sync(A)
    # the profiler wrote something under the log dir
    found = [p for _, _, fs in os.walk(tmp_path) for p in fs]
    assert found, "profiler trace produced no files"
    igg.finalize_global_grid()
