"""Profiling subsystem: trace capture, the pure-Python XPlane decoder, and
the comm/compute overlap analysis (the quantitative analog of the
reference's structure-the-streams-for-Nsight approach,
`/root/reference/src/update_halo.jl:207`)."""

import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _field(fn: int, wt: int, payload) -> bytes:
    tag = _varint(fn << 3 | wt)
    if wt == 0:
        return tag + _varint(payload)
    return tag + _varint(len(payload)) + payload


def _event(mid, offset_ps, dur_ps):
    return (_field(1, 0, mid) + _field(2, 0, offset_ps)
            + _field(3, 0, dur_ps))


def _line(name, ts_ns, events):
    body = _field(2, 2, name.encode()) + _field(3, 0, ts_ns)
    for ev in events:
        body += _field(4, 2, ev)
    return body


def _meta(mid, name):
    return _field(1, 0, mid) + _field(2, 2, name.encode())


def _plane(name, lines, metas):
    body = _field(2, 2, name.encode())
    for ln in lines:
        body += _field(3, 2, ln)
    for mid, m in metas:
        body += _field(4, 2, _field(1, 0, mid) + _field(2, 2, m))
    return body


def test_xplane_wire_decoder(tmp_path):
    """Decode a hand-encoded XSpace: plane/line/event names, metadata
    resolution, and line-timestamp + offset arithmetic."""
    from implicitglobalgrid_tpu.utils.xplane import parse_xspace

    metas = [(1, _meta(1, "%f = f32[8]{0} fusion(%a), calls=%fc")),
             (2, _meta(2, "%cp = collective-permute-start(%x)"))]
    lines = [
        _line("XLA Ops", 10, [_event(1, 5000, 2000)]),
        _line("Async XLA Ops", 10, [_event(2, 6000, 4000)]),
    ]
    space = _field(1, 2, _plane("/device:TPU:0", lines, metas))
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(space)

    planes = parse_xspace(str(p))
    assert len(planes) == 1 and planes[0].name == "/device:TPU:0"
    ops, async_ops = planes[0].lines
    assert ops.name == "XLA Ops" and async_ops.name == "Async XLA Ops"
    (ev,) = ops.events
    assert "fusion" in ev.name
    assert ev.start_ps == 10 * 1000 + 5000 and ev.duration_ps == 2000
    (aev,) = async_ops.events
    assert "collective-permute" in aev.name


def _write_run(tmp_path, planes_bytes):
    run = tmp_path / "plugins" / "profile" / "run1"
    run.mkdir(parents=True)
    blob = b"".join(_field(1, 2, p) for p in planes_bytes)
    (run / "host.xplane.pb").write_bytes(blob)


def test_overlap_stats_arithmetic(tmp_path):
    """overlap_stats on a synthetic capture: compute [15, 17)us and an
    async collective span [16, 20)us -> 1us of the 4us comm hidden."""
    from implicitglobalgrid_tpu.utils.profiling import overlap_stats

    metas = [(1, _meta(1, "%f = f32[8]{0} fusion(%a), calls=%fc")),
             (2, _meta(2, "%cp = collective-permute-start(%x)")),
             (3, _meta(3, "%cs = (f32[8]{0}, u32[]) copy-start(%a)"))]
    lines = [
        _line("XLA Ops", 0, [_event(1, 15_000_000, 2_000_000)]),
        # the async copy span is NOT compute: it must not count toward
        # hidden communication (the core is idle under it)
        _line("Async XLA Ops", 0, [_event(2, 16_000_000, 4_000_000),
                                   _event(3, 18_000_000, 9_000_000)]),
    ]
    _write_run(tmp_path, [_plane("/device:TPU:0", lines, metas)])

    stats = overlap_stats(str(tmp_path))
    s = stats["TPU:0"]
    assert abs(s["compute_us"] - 2.0) < 1e-9
    assert abs(s["comm_us"] - 4.0) < 1e-9
    assert abs(s["hidden_comm_us"] - 1.0) < 1e-9
    assert abs(s["exposed_comm_us"] - 3.0) < 1e-9
    assert abs(s["overlap_frac"] - 0.25) < 1e-9
    assert abs(s["busy_us"] - 5.0) < 1e-9  # union [15,17) u [16,20)


def test_host_overlap_fallback(tmp_path):
    """A capture with NO /device: planes (the XLA:CPU backend) falls back
    to the runtime thread-pool lines: ppermute thunk spans + Rendezvous
    waits are comm, lowercase HLO thunk spans are compute, and C++
    infrastructure / 'Wait:' / 'end:' markers / 'while' containers are
    neither."""
    from implicitglobalgrid_tpu.utils.profiling import overlap_stats

    metas = [(1, _meta(1, "wrapped_add")),
             (2, _meta(2, "ppermute.42")),
             (3, _meta(3, "ThunkExecutor::Execute")),
             (4, _meta(4, "Wait: pending_threads=1/8")),
             (5, _meta(5, "end: ppermute.42")),
             (6, _meta(6, "Rendezvous")),
             (7, _meta(7, "while.3"))]
    lines = [
        # thread 1: compute [0,4)us, ppermute comm [2,8)us, infra ignored;
        # the 'end: ppermute' marker sits OUTSIDE every other span at
        # [9.5,10.5)us so a misclassification (as comm OR compute) would
        # change the totals below
        _line("tf_XLAEigen/1", 0, [_event(1, 0, 4_000_000),
                                   _event(2, 2_000_000, 6_000_000),
                                   _event(3, 0, 10_000_000),
                                   _event(5, 9_500_000, 1_000_000)]),
        # thread 2: Rendezvous comm [6,9)us; 'while'/'Wait:' ignored
        _line("tf_XLAEigen/2", 0, [_event(6, 6_000_000, 3_000_000),
                                   _event(7, 0, 9_000_000),
                                   _event(4, 0, 10_000_000)]),
    ]
    _write_run(tmp_path, [_plane("/host:CPU", lines, metas)])

    stats = overlap_stats(str(tmp_path))
    s = stats["CPU:threadpool"]
    assert abs(s["compute_us"] - 4.0) < 1e-9
    assert abs(s["comm_us"] - 7.0) < 1e-9        # [2,8) u [6,9)
    assert abs(s["hidden_comm_us"] - 2.0) < 1e-9  # comm over compute [2,4)
    assert abs(s["exposed_comm_us"] - 5.0) < 1e-9
    assert abs(s["busy_us"] - 9.0) < 1e-9


def test_device_planes_preempt_host_fallback(tmp_path):
    """When a device plane exists, host thread-pool lines are ignored —
    the fallback is only for captures with no device attribution."""
    from implicitglobalgrid_tpu.utils.profiling import overlap_stats

    dev_metas = [(1, _meta(1, "%f = f32[8]{0} fusion(%a)"))]
    dev_lines = [_line("XLA Ops", 0, [_event(1, 0, 2_000_000)])]
    host_metas = [(1, _meta(1, "ppermute.7"))]
    host_lines = [_line("tf_XLAEigen/1", 0, [_event(1, 0, 5_000_000)])]
    _write_run(tmp_path, [_plane("/device:TPU:0", dev_lines, dev_metas),
                          _plane("/host:CPU", host_lines, host_metas)])

    stats = overlap_stats(str(tmp_path))
    assert "TPU:0" in stats and "CPU:threadpool" not in stats


def test_op_breakdown_host_fallback(tmp_path):
    """A capture with NO /device: op events (the XLA:CPU backend) must
    fall back to the host thread-pool lines instead of returning [] —
    thunk spans and comm machinery aggregate by kind, infrastructure and
    completion markers stay excluded (satellite of ISSUE 3)."""
    from implicitglobalgrid_tpu.utils.profiling import op_breakdown

    metas = [(1, _meta(1, "wrapped_add")),
             (2, _meta(2, "ppermute.42")),
             (3, _meta(3, "ThunkExecutor::Execute")),
             (4, _meta(4, "end: ppermute.42")),
             (5, _meta(5, "fusion.3")),
             (6, _meta(6, "Rendezvous")),
             (7, _meta(7, "while.3"))]
    lines = [
        _line("tf_XLAEigen/1", 0, [_event(1, 0, 3_000_000),
                                   _event(1, 4_000_000, 1_000_000),
                                   _event(2, 2_000_000, 6_000_000),
                                   _event(3, 0, 10_000_000),
                                   _event(4, 9_500_000, 1_000_000)]),
        _line("tf_XLAEigen/2", 0, [_event(5, 0, 2_000_000),
                                   _event(6, 6_000_000, 3_000_000),
                                   _event(7, 0, 9_000_000)]),
    ]
    _write_run(tmp_path, [_plane("/host:CPU", lines, metas)])

    rows = op_breakdown(str(tmp_path))
    by_kind = {k: (us, c) for k, us, c in rows}
    assert by_kind["wrapped_add"] == (4.0, 2)
    assert by_kind["ppermute"] == (6.0, 1)
    assert by_kind["fusion"] == (2.0, 1)
    assert by_kind["Rendezvous"] == (3.0, 1)
    # infrastructure, completion markers, and the while container excluded
    assert not any("::" in k or k.startswith("end") or k == "while"
                   for k in by_kind)
    # first row is the biggest time sink
    assert rows[0][0] == "ppermute"


def test_op_breakdown_synthetic(tmp_path):
    from implicitglobalgrid_tpu.utils.profiling import op_breakdown

    metas = [(1, _meta(1, "%f = f32[8]{0} fusion(%a), calls=%fc")),
             (3, _meta(3, "%d = f32[8]{0} copy-done(%cs)"))]
    lines = [_line("XLA Ops", 0, [_event(1, 0, 3000), _event(1, 5000, 1000),
                                  _event(3, 9000, 500)])]
    _write_run(tmp_path, [_plane("/device:TPU:0", lines, metas)])

    rows = op_breakdown(str(tmp_path))
    assert rows[0][0] == "fusion" and rows[0][2] == 2
    assert any(k == "copy-done" for k, _, _ in rows)


def test_op_kind_parsing():
    from implicitglobalgrid_tpu.utils.profiling import _op_kind

    assert _op_kind("%f.1 = f32[512,512]{1,0:T(8,128)} fusion(%a)") == "fusion"
    # tuple-typed ops (multi-output fusions, async starts) aggregate too
    assert _op_kind("%f = (f32[8]{0}, f32[8]{0}) fusion(%a, %b)") == "fusion"
    assert _op_kind(
        "%cs = (f32[8]{0}, u32[]) collective-permute-start(%x)"
    ) == "collective-permute-start"
    assert _op_kind("jit_matmul(123456)") == "jit_matmul"
    # short-form names from real captures aggregate by kind
    assert _op_kind("while.3") == "while"
    assert _op_kind("copy.15") == "copy"


def test_comm_classified_by_op_kind(tmp_path):
    """A fusion CONSUMING a collective's result is compute, not comm."""
    from implicitglobalgrid_tpu.utils.profiling import overlap_stats

    metas = [(1, _meta(1, "%add.7 = f32[8]{0} fusion("
                          "%collective-permute-done.2, %y)"))]
    lines = [_line("XLA Ops", 0, [_event(1, 0, 2_000_000)])]
    _write_run(tmp_path, [_plane("/device:TPU:0", lines, metas)])
    s = overlap_stats(str(tmp_path))["TPU:0"]
    assert s["comm_us"] == 0.0 and abs(s["compute_us"] - 2.0) < 1e-9


@pytest.mark.slow
def test_trace_and_annotate(tmp_path):
    """slow (tier-1 budget, ISSUE 8 trim): a REAL profiler capture costs
    ~18 s on the shared box; the decoder/arithmetic paths it feeds keep
    their fast synthetic-capture coverage above (xplane decoder,
    overlap_stats, op_breakdown, host fallbacks)."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    A = igg.device_put_g(np.ones((8, 8, 8), np.float32))
    with igg.trace(str(tmp_path)):
        with igg.annotate("halo"):
            A = igg.update_halo(A)
        igg.sync(A)
    # the profiler wrote something under the log dir
    found = [p for _, _, fs in os.walk(tmp_path) for p in fs]
    assert found, "profiler trace produced no files"
    # the decoder reads the real capture; device planes exist only when an
    # accelerator backend registered — assert the analysis is well-formed
    # either way (values finite, hidden comm bounded by total comm)
    stats = igg.overlap_stats(str(tmp_path))
    for s in stats.values():
        assert s["busy_us"] >= 0 and s["comm_us"] >= 0
        assert s["hidden_comm_us"] <= s["comm_us"] + 1e-9
    igg.op_breakdown(str(tmp_path))
    igg.finalize_global_grid()
