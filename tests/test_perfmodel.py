"""Performance-oracle tests (ISSUE 6): the analytical cost model, machine
calibration, the live drift detector, and the perf-history gate.

The acceptance bar: the model's regime classification responds correctly
(and deterministically) to the machine coefficients, the drift detector
catches an injected host-side slowdown at the right chunk and the mesh
layer attributes it to the right process, and `tools perfdb check`
detects an injected 30% regression against a synthetic history while
passing on noise."""

import json
import time

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.telemetry


def _init(nx=8, **kw):
    igg.init_global_grid(nx, nx, nx, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True,
                         **kw)


def _profile(membw=10.0, flops=10.0, link=1.0, lat=1e-5):
    return igg.MachineProfile(
        membw_GBps=membw, flops_G=flops,
        axes={a: {"GBps": link, "latency_s": lat}
              for a in ("gx", "gy", "gz")})


# ---------------------------------------------------------------------------
# The analytical model
# ---------------------------------------------------------------------------

def test_predict_step_structure():
    _init()
    T, Cp = igg.ones_g(dtype=np.float32), igg.ones_g(dtype=np.float32)
    pred = igg.predict_step("diffusion3d", (T, Cp), profile=_profile())
    assert pred["model"] == "diffusion3d"
    assert pred["local_cells"] == 8 ** 3  # init takes LOCAL block sizes
    assert set(pred["comm"]) == {"gx", "gy", "gz"}
    for rec in pred["comm"].values():
        assert rec["s"] == pytest.approx(rec["latency_s"] + rec["wire_s"])
        assert rec["per_link_bytes"] > 0
    assert pred["step_s"] == pytest.approx(
        pred["compute"]["s"] + pred["exposed_comm_s"])
    assert pred["bound"] in ("compute", "bandwidth", "latency")
    # deterministic: same inputs -> identical record (stable verdict)
    assert igg.predict_step("diffusion3d", (T, Cp),
                            profile=_profile()) == pred
    with pytest.raises(InvalidArgumentError, match="unknown model"):
        igg.predict_step("nope", (T,))


def test_bound_classification_tracks_coefficients():
    """The roofline verdict must follow the dominant machine term —
    the knob-picking signal the auto-tuner will search over."""
    _init()
    T, Cp = igg.ones_g(dtype=np.float32), igg.ones_g(dtype=np.float32)
    fields = (T, Cp)
    # sky-high link latency -> collective launches dominate
    p = igg.predict_step("diffusion3d", fields,
                         profile=_profile(lat=1.0))
    assert p["bound"] == "latency"
    # starved wire bandwidth -> wire bytes dominate
    p = igg.predict_step("diffusion3d", fields,
                         profile=_profile(link=1e-9, lat=0.0))
    assert p["bound"] == "bandwidth" and p["bound_detail"] == "wire"
    # starved HBM with fast links -> memory-bandwidth bound
    p = igg.predict_step("diffusion3d", fields,
                         profile=_profile(membw=1e-9, link=1e9, lat=0.0))
    assert p["bound"] == "bandwidth" and p["bound_detail"] == "hbm"
    # tiny FLOP rate with everything else fast -> compute bound
    p = igg.predict_step("diffusion3d", fields,
                         profile=_profile(flops=1e-9, membw=1e9,
                                          link=1e9, lat=0.0))
    assert p["bound"] == "compute"


def test_comm_every_and_overlap_pricing():
    _init()
    T, Cp = igg.ones_g(dtype=np.float32), igg.ones_g(dtype=np.float32)
    prof = _profile(lat=1e-3)
    p1 = igg.predict_step("diffusion3d", (T, Cp), profile=prof)
    p4 = igg.predict_step("diffusion3d", (T, Cp), profile=prof,
                          comm_every=4)
    # the deep-halo cadence amortizes the exchange over k steps
    for ax in p1["comm"]:
        assert p4["comm"][ax]["latency_s"] == pytest.approx(
            p1["comm"][ax]["latency_s"] / 4)
    # overlap credits comm that hides behind INTERIOR compute (the shell
    # update serializes before the collectives — priced from the slab
    # geometry, so the credit shrinks with the interior fraction)
    po = igg.predict_step("diffusion3d", (T, Cp), profile=prof,
                          overlap=True)
    assert 0.0 < po["interior_frac"] < 1.0
    assert po["exposed_comm_s"] == pytest.approx(
        max(0.0, po["comm_s"] - po["compute"]["s"] * po["interior_frac"]))
    assert po["step_s"] <= p1["step_s"]
    assert p1["interior_frac"] == 1.0  # no overlap: nothing serializes


def test_per_axis_comm_every_pricing():
    """ISSUE 13: the latency term divides by EACH axis's own cadence —
    a z-only cadence amortizes only the z axis while x/y keep their
    per-step launches — and the record carries the canonical cadence."""
    _init()
    T, Cp = igg.ones_g(dtype=np.float32), igg.ones_g(dtype=np.float32)
    prof = _profile(lat=1e-3)
    p1 = igg.predict_step("diffusion3d", (T, Cp), profile=prof)
    pz = igg.predict_step("diffusion3d", (T, Cp), profile=prof,
                          comm_every="z:4")
    assert pz["comm_every"] == "z:4"
    assert pz["comm"]["gz"]["comm_every"] == 4
    assert pz["comm"]["gz"]["latency_s"] == pytest.approx(
        p1["comm"]["gz"]["latency_s"] / 4)
    for ax in ("gx", "gy"):
        assert pz["comm"][ax]["latency_s"] == pytest.approx(
            p1["comm"][ax]["latency_s"])
    # every accepted spelling resolves to one pricing
    assert igg.predict_step("diffusion3d", (T, Cp), profile=prof,
                            comm_every={"gz": 4}) == pz
    # a deep cadence switches acoustic to the deep runner's ONE 4-field
    # round per due axis (vs the per-step V + P rounds)
    state = tuple(igg.ones_g(dtype=np.float32) for _ in range(4))
    a1 = igg.predict_step("acoustic3d", state, profile=prof)
    a2 = igg.predict_step("acoustic3d", state, profile=prof,
                          comm_every=2)
    assert a1["comm"]["gz"]["ppermute_pairs"] == 2.0
    assert a2["comm"]["gz"]["ppermute_pairs"] == 1.0


def test_bound_detail_names_latency_dominant_axis():
    """A latency-bound verdict points at the AXIS whose cadence the
    tuner should turn (``comm_every[z]``), not an undifferentiated
    global knob — the hierarchical ICI+DCN case the per-axis cadence
    exists for."""
    _init()
    T, Cp = igg.ones_g(dtype=np.float32), igg.ones_g(dtype=np.float32)
    prof = igg.MachineProfile(
        membw_GBps=1e3, flops_G=1e6,
        axes={"gx": {"GBps": 45.0, "latency_s": 5e-6},
              "gy": {"GBps": 45.0, "latency_s": 5e-6},
              "gz": {"GBps": 45.0, "latency_s": 5e-3}})
    p = igg.predict_step("diffusion3d", (T, Cp), profile=prof)
    assert p["bound"] == "latency"
    assert p["bound_detail"] == "comm_every[z]"
    # amortizing exactly that axis melts the verdict's latency term
    pz = igg.predict_step("diffusion3d", (T, Cp), profile=prof,
                          comm_every="z:8")
    assert pz["comm_s"] < p["comm_s"]


def test_wire_dtype_halves_wire_bytes():
    _init()
    T = igg.ones_g(dtype=np.float32)
    prof = _profile(lat=0.0)
    full = igg.predict_step("diffusion3d", (T,), profile=prof)
    half = igg.predict_step("diffusion3d", (T,), profile=prof,
                            wire_dtype="bfloat16")
    for ax in full["comm"]:
        assert half["comm"][ax]["per_link_bytes"] * 2 \
            == full["comm"][ax]["per_link_bytes"]


# ---------------------------------------------------------------------------
# Calibration + profile persistence
# ---------------------------------------------------------------------------

def test_calibrate_roundtrip(tmp_path):
    _init()
    path = str(tmp_path / "profile.json")
    prof = igg.calibrate_machine(path, elems_per_device=1 << 12,
                                 link_bytes=(1 << 10, 1 << 14), c1=2)
    assert prof.source == "calibrated"
    assert prof.membw_GBps > 0 and prof.flops_G > 0
    assert set(prof.axes) == {"gx", "gy", "gz"}  # every axis multi-shard
    for rec in prof.axes.values():
        assert rec["GBps"] > 0 and rec["latency_s"] >= 0
    loaded = igg.load_machine_profile(path)
    assert loaded.membw_GBps == prof.membw_GBps
    assert loaded.axes == prof.axes
    assert loaded.device["n_shards"] == 8
    # a calibrated profile feeds the model end to end
    T = igg.ones_g(dtype=np.float32)
    pred = igg.predict_step("diffusion3d", (T,), profile=loaded)
    assert pred["profile_source"] == "calibrated"
    assert 0 < pred["step_s"] < 60.0


def test_default_profile_axis_fallback():
    prof = igg.MachineProfile(membw_GBps=10.0, flops_G=10.0,
                              axes={"gx": {"GBps": 2.0,
                                           "latency_s": 1e-5}})
    # an axis the profile never measured falls back to the measured mean
    assert prof.axis("gy")["GBps"] == 2.0
    empty = igg.MachineProfile(membw_GBps=1.0, flops_G=1.0, axes={})
    assert empty.axis("gx")["GBps"] > 0


def test_load_machine_profile_rejects_garbage(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{\"not\": \"a profile\"}")
    with pytest.raises(InvalidArgumentError):
        igg.load_machine_profile(str(p))
    with pytest.raises(InvalidArgumentError):
        igg.load_machine_profile(str(tmp_path / "missing.json"))


# ---------------------------------------------------------------------------
# The live drift detector
# ---------------------------------------------------------------------------

def test_perfwatch_flags_only_clear_drift():
    igg.reset_metrics()
    w = igg.PerfWatch(window=8, zmax=4.0, model_step_s=1e-3)
    # warm-up + stable plateau (2% jitter): never flags
    for i in range(12):
        jitter = 1.0 + 0.02 * ((-1) ** i)
        assert w.observe(chunk=i, step_begin=i, step_end=i + 1, n=10,
                         exec_s=0.01 * jitter) is None
    # cold chunk at 10x: gauges move, no verdict, baseline unpolluted
    assert w.observe(chunk=12, step_begin=12, step_end=13, n=10,
                     exec_s=0.1, cold=True) is None
    # genuine 10x drift: flagged with the right chunk and a big z
    v = w.observe(chunk=13, step_begin=13, step_end=14, n=10, exec_s=0.1)
    assert v is not None and v["chunk"] == 13 and v["z"] > 4.0
    # per-step = 0.1/10 = 0.01 s against the 1e-3 model -> ratio 10
    assert v["ratio"] == pytest.approx(10.0)
    reg = igg.metrics_registry()
    assert reg.get("igg_perf_step_seconds").value() == pytest.approx(0.01)
    assert reg.get("igg_perf_regressions_total").value() == 1.0
    assert reg.get("igg_perf_model_ratio").value() == pytest.approx(10.0)
    with pytest.raises(InvalidArgumentError):
        igg.PerfWatch(window=1)


def test_perfwatch_small_window_still_detects():
    """window < the default min_samples must clamp, not silently disable
    the z-test (a maxlen-4 deque can never hold 5 samples): a 1000x
    drift after a 4-chunk warm-up is flagged."""
    w = igg.PerfWatch(window=4, zmax=4.0)
    for i in range(6):
        assert w.observe(chunk=i, step_begin=i, step_end=i + 1, n=10,
                         exec_s=0.01) is None
    v = w.observe(chunk=6, step_begin=6, step_end=7, n=10, exec_s=10.0)
    assert v is not None and v["chunk"] == 6 and v["z"] > 4.0


def test_driver_emits_perf_regression_on_injected_slowdown(tmp_path):
    """Acceptance: an injected host-side stall inside one chunk's
    dispatch makes the driver emit perf_regression for exactly that
    region, and run_report's perf section carries it + the model."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime import health

    _init()
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    calls = [0]
    orig = health.make_guarded_runner

    def stalling(*a, **kw):
        runner = orig(*a, **kw)

        def wrapped(*args):
            calls[0] += 1
            if calls[0] == 9:  # well past the watch's warm-up
                time.sleep(0.3)
            return runner(*args)
        return wrapped

    jsonl = str(tmp_path / "fr.jsonl")
    health.make_guarded_runner = stalling
    igg.start_flight_recorder(jsonl)
    try:
        igg.run_resilient(step, {"T": T, "Cp": Cp}, 60, nt_chunk=5,
                          key="perf_e2e", perf_model=1e-3)
    finally:
        igg.stop_flight_recorder()
        health.make_guarded_runner = orig

    evs = igg.read_flight_events(jsonl)
    assert [e["step_s"] for e in evs if e["kind"] == "perf_model"] \
        == [1e-3]
    regs = [e for e in evs if e["kind"] == "perf_regression"]
    assert regs and any(r["chunk"] == 8 for r in regs), regs
    assert all(r["z"] > 4.0 for r in regs)
    rep = igg.run_report(jsonl)
    assert rep["perf"]["regressions"] == len(regs)
    assert rep["perf"]["model_step_s"] == 1e-3
    assert rep["perf"]["worst_z"] > 4.0
    assert any(s["kind"] == "perf_regression" for s in rep["sequence"])


def test_driver_perf_window_zero_disables():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    _init()
    igg.reset_metrics()
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    igg.run_resilient(step, {"T": T, "Cp": Cp}, 10, nt_chunk=5,
                      key="perf_off", perf_window=0)
    # the gauge never moved (reset_metrics keeps registrations, so the
    # family may exist from earlier tests — disabled means value 0)
    fam = igg.metrics_registry().get("igg_perf_step_seconds")
    assert fam is None or fam.value() == 0.0
    with pytest.raises(InvalidArgumentError, match="perf_model"):
        igg.run_resilient(step, {"T": T, "Cp": Cp}, 5, nt_chunk=5,
                          key="perf_bad", perf_model="nonsense")


# ---------------------------------------------------------------------------
# Mesh-wide attribution of drift flags
# ---------------------------------------------------------------------------

def _synthetic_two_proc(perf_procs=(1,), n_chunks=10, reg_chunk=7):
    """Two clock-aligned per-process streams with one perf_regression
    chunk flagged by ``perf_procs`` (same idiom as the aggregation
    tests: fabricated event dicts, no devices)."""
    events = []
    for proc in (0, 1):
        seq = 0

        def ev(kind, t, **kw):
            nonlocal seq
            e = {"kind": kind, "t": t, "run": "r1", "proc": proc,
                 "seq": seq, **kw}
            seq += 1
            return e

        events.append(ev("recorder_open", 0.0, wall=1000.0))
        for c in range(n_chunks):
            t = 1.0 + c
            events.append(ev("chunk", t, chunk=c, step_begin=c * 5,
                             step_end=c * 5 + 5, n=5, ok=True,
                             exec_s=0.5, build_s=0.001))
            if c == reg_chunk and proc in perf_procs:
                events.append(ev("perf_regression", t, chunk=c,
                                 step_begin=c * 5, step_end=c * 5 + 5,
                                 per_step_s=0.5, baseline_s=0.1,
                                 z=9.0, ratio=None))
    return events


def test_straggler_report_attributes_localized_regression():
    rep = igg.straggler_report(igg.aggregate_events(
        _synthetic_two_proc(perf_procs=(1,)))["events"])
    pr = rep["perf_regressions"]
    assert pr["events"] == 1
    assert pr["per_process"] == {1: 1}
    assert pr["chunks"] == [{"chunk": 7, "procs": [1],
                             "scope": "process", "max_z": 9.0}]
    assert pr["localized"] == 1 and pr["mesh_wide"] == 0


def test_straggler_report_flags_mesh_wide_slowdown():
    """Every process drifting together is a MESH-wide event — the case
    barrier-arrival spreads are structurally blind to."""
    rep = igg.straggler_report(igg.aggregate_events(
        _synthetic_two_proc(perf_procs=(0, 1)))["events"])
    pr = rep["perf_regressions"]
    assert pr["mesh_wide"] == 1 and pr["localized"] == 0
    assert pr["chunks"][0]["scope"] == "mesh-wide"
    assert rep["summary"]["chunks"] == 10  # straggler analysis unharmed


def test_straggler_report_no_perf_events_is_none():
    rep = igg.straggler_report(igg.aggregate_events(
        _synthetic_two_proc(perf_procs=()))["events"])
    assert rep["perf_regressions"] is None


# ---------------------------------------------------------------------------
# The perf-history database and gate
# ---------------------------------------------------------------------------

def _history(db, runs=6, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(runs):
        igg.perfdb_add(db, [
            {"metric": "diffusion3D_f32_cell_updates_per_s_per_chip",
             "value": 100.0 * (1 + 0.04 * rng.uniform(-1, 1)),
             "platform": "cpu"},
            {"metric": "telemetry_overhead_frac",
             "value": 1e-3 * (1 + 0.1 * rng.uniform(-1, 1))},
            {"metric": "update_halo_coalesced_speedup_4fields",
             "value": 5.0 + rng.uniform(-0.2, 0.2)},
        ])


def test_perfdb_detects_injected_regression_and_passes_noise(tmp_path):
    db = str(tmp_path / "hist.jsonl")
    _history(db)
    noise = [{"metric": "diffusion3D_f32_cell_updates_per_s_per_chip",
              "value": 97.0},
             {"metric": "telemetry_overhead_frac", "value": 1.1e-3},
             {"metric": "update_halo_coalesced_speedup_4fields",
              "value": 4.9}]
    rep = igg.perfdb_check(db, noise)
    assert rep["ok"] and rep["checked"] == 3 and not rep["regressions"]
    # injected 30%+ throughput drop -> fails, right metric, direction
    bad = [dict(noise[0], value=69.0)] + noise[1:]
    rep = igg.perfdb_check(db, bad)
    assert not rep["ok"]
    assert [r["metric"] for r in rep["regressions"]] \
        == ["diffusion3D_f32_cell_updates_per_s_per_chip"]
    assert rep["regressions"][0]["direction"] == "higher"
    # overhead going UP 10x is a regression too (lower-better direction)
    worse_overhead = noise[:1] + [dict(noise[1], value=1e-2)] + noise[2:]
    rep = igg.perfdb_check(db, worse_overhead)
    assert [r["metric"] for r in rep["regressions"]] \
        == ["telemetry_overhead_frac"]


def test_perfdb_skips_unknown_and_fresh_metrics(tmp_path):
    db = str(tmp_path / "hist.jsonl")
    _history(db, runs=1)  # below min_history
    rows = [{"metric": "diffusion3D_f32_cell_updates_per_s_per_chip",
             "value": 1.0},  # 100x regression, but only 1 history point
            {"metric": "perf_model_ratio_diffusion3D_f32", "value": 1.4}]
    rep = igg.perfdb_check(db, rows)
    assert rep["ok"]
    reasons = {s["metric"]: s["reason"] for s in rep["skipped"]}
    assert reasons["diffusion3D_f32_cell_updates_per_s_per_chip"] \
        == "insufficient-history"
    assert reasons["perf_model_ratio_diffusion3D_f32"] \
        == "unknown-direction"
    # rows with null values never poison the db
    with pytest.raises(InvalidArgumentError):
        igg.perfdb_add(db, [{"metric": "x", "value": None}])


def test_perfdb_cli_gate(tmp_path, capsys):
    """The CI hook: `tools perfdb check` exits 1 on an injected 30%
    regression, 0 on noise (the tier-1 form of the bench self-gate)."""
    from implicitglobalgrid_tpu.tools import _cli

    db = str(tmp_path / "hist.jsonl")
    _history(db)
    good = str(tmp_path / "good.json")
    bad = str(tmp_path / "bad.json")
    with open(good, "w") as f:
        json.dump([{"metric":
                    "diffusion3D_f32_cell_updates_per_s_per_chip",
                    "value": 102.0}], f)
    with open(bad, "w") as f:
        json.dump([{"metric":
                    "diffusion3D_f32_cell_updates_per_s_per_chip",
                    "value": 65.0}], f)
    assert _cli(["perfdb", "check", good, "--db", db]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert _cli(["perfdb", "check", bad, "--db", db]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["regressions"][0]["metric"] \
        == "diffusion3D_f32_cell_updates_per_s_per_chip"
    # add appends exactly one record
    assert _cli(["perfdb", "add", good, "--db", db, "--note", "ci"]) == 0
    capsys.readouterr()
    hist = igg.telemetry.perfdb_load(db)
    assert len(hist) == 7 and hist[-1]["meta"]["note"] == "ci"


def test_perfdb_tolerates_torn_final_line(tmp_path):
    from implicitglobalgrid_tpu.telemetry import perfdb_load

    db = str(tmp_path / "hist.jsonl")
    _history(db, runs=2)
    with open(db, "a") as f:
        f.write('{"ts": 1, "metrics": {"x":')  # crash mid-append
    assert len(perfdb_load(db)) == 2
    with open(db, "w") as f:
        f.write('{"broken\n{"ts": 2, "metrics": {}}\n')
    with pytest.raises(InvalidArgumentError, match="corrupt interior"):
        perfdb_load(db)


# ---------------------------------------------------------------------------
# Ephemeral-port metrics server (satellite)
# ---------------------------------------------------------------------------

def test_metrics_server_ephemeral_port_gauge():
    igg.reset_metrics()
    srv = igg.start_metrics_server(0)
    try:
        assert srv.port > 0
        g = igg.metrics_registry().get("igg_metrics_server_port")
        assert g.value() == srv.port
    finally:
        igg.stop_metrics_server()
    assert igg.metrics_registry().get(
        "igg_metrics_server_port").value() == 0


def test_run_resilient_metrics_port_zero_binds_ephemeral():
    """run_resilient(metrics_port=0): no hard-coded port, the actual
    bound port is readable mid-run via the gauge + metrics_server()."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    _init()
    igg.reset_metrics()
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    seen = []

    def on_report(rep):
        srv = igg.metrics_server()
        seen.append((srv.port if srv else None,
                     igg.metrics_registry().get(
                         "igg_metrics_server_port").value()))

    igg.run_resilient(step, {"T": T, "Cp": Cp}, 5, nt_chunk=5,
                      key="port0", metrics_port=0, on_report=on_report)
    assert seen and seen[0][0] > 0
    assert seen[0][1] == seen[0][0]  # gauge == actual bound port
    assert igg.metrics_server() is None  # stopped with the run
