"""Property tests of the canonical wire schema (`ops/wire.py`) — ONE
parametrized suite covering layout round-trips for every dtype x format x
tier combination, so the XLA coalesced pack and the Pallas fused pack can
never drift apart (they are the same `WireSchema` program; the Pallas
tier is exercised through `wire_pack_pallas` in interpret mode).

Tier-1 keeps one fast representative per property; the full
dtype x format x tier matrix rides the ``slow`` marker (ROADMAP tier-1
budget note).
"""

import numpy as np
import pytest

from implicitglobalgrid_tpu.ops.precision import (
    SCALE_BYTES, WireFormat, quant_slab_bytes,
)
from implicitglobalgrid_tpu.ops.wire import slab_schema, schema_for_fields
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError


def _slabs(shapes, dtype, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    out = []
    for s in shapes:
        a = rng.standard_normal(s) * 3.0
        out.append(jnp.asarray(a).astype(dtype))
    return out


def _roundtrip(schema, slabs, pallas=False):
    mode = (True, True) if pallas else None
    buf = schema.pack(slabs, pallas_mode=mode)
    return buf, schema.unpack(buf)


def _assert_exact_roundtrip(shapes, dtype, dim, pallas=False):
    import jax.numpy as jnp

    slabs = _slabs(shapes, dtype)
    schema = slab_schema(dim, shapes, dtype)
    assert schema.fmt is None and not schema.is_quant
    buf, back = _roundtrip(schema, slabs, pallas=pallas)
    # byte accounting is exact: the packed buffer IS payload_bytes long
    assert buf.size * buf.dtype.itemsize == schema.payload_bytes
    assert buf.dtype == jnp.asarray(slabs[0]).dtype
    for a, b in zip(slabs, back):
        assert b.shape == a.shape and b.dtype == a.dtype
        assert np.array_equal(np.asarray(a), np.asarray(b))


# -- fast tier-1 representatives (one per property) -------------------------

def test_exact_roundtrip_slab_layout():
    """Exact wire, uniform cross-shapes -> the SLAB layout (concat along
    the exchange axis, no ravel): bitwise round-trip, byte-exact
    accounting."""
    shapes = [(1, 6, 8)] * 4
    schema = slab_schema(0, shapes, np.float32)
    assert schema.layout == "slab"
    _assert_exact_roundtrip(shapes, np.float32, 0)


def test_exact_roundtrip_flat_layout_staggered():
    """Mixed (staggered) cross-shapes force the FLAT layout — the fused
    multi-field packs (P, Vx, Vy, Vz): still a bitwise round-trip."""
    shapes = [(1, 6, 8), (1, 7, 8), (1, 6, 9)]
    schema = slab_schema(0, shapes, np.float32)
    assert schema.layout == "flat"
    _assert_exact_roundtrip(shapes, np.float32, 0)


def test_quant_roundtrip_matches_per_slab_codec():
    """int8 wire: the packed buffer is slabs + the SCALE_BYTES f32 tail,
    and unpack reproduces the per-slab quantize/dequantize reference
    EXACTLY (each slab against its own scale); constant slabs round-trip
    bit-for-bit."""
    from implicitglobalgrid_tpu.ops.precision import (
        dequantize_slab, quantize_slab,
    )

    fmt = WireFormat("int8")
    shapes = [(1, 4, 8), (1, 4, 8)]
    slabs = _slabs(shapes, np.float32)
    schema = slab_schema(2, shapes, np.float32, fmt)
    assert schema.layout == "flat" and schema.is_quant
    buf, back = _roundtrip(schema, slabs)
    assert buf.size == schema.payload_bytes \
        == sum(quant_slab_bytes(32, fmt) for _ in shapes) + 2 * SCALE_BYTES
    for a, b in zip(slabs, back):
        q, scale = quantize_slab(a.reshape(-1), fmt)
        ref = dequantize_slab(q, scale, a.size, fmt, a.dtype).reshape(a.shape)
        assert np.array_equal(np.asarray(ref), np.asarray(b))
    # constant slabs are EXACT through the quant codec
    import jax.numpy as jnp

    const = [jnp.full(s, 2.5, np.float32) for s in shapes]
    _, back = _roundtrip(schema, const)
    for a, b in zip(const, back):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pallas_pack_matches_xla_pack():
    """The fused Pallas pack (interpret mode — the TPU tier's kernel) is
    BIT-IDENTICAL to the XLA concat pack: one schema, two executors."""
    import jax.numpy as jnp

    shapes = [(1, 8, 128)] * 3
    slabs = _slabs(shapes, np.float32)
    schema = slab_schema(0, shapes, np.float32)
    a = schema.pack(slabs)
    b = schema.pack(slabs, pallas_mode=(True, True))
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(a),
                          np.asarray(jnp.concatenate(slabs, axis=0)))


def test_schema_for_fields_matches_plan_geometry():
    """`schema_for_fields` derives slab shapes from field shapes + hw —
    the single geometry rule the static plan prices. Byte accounting must
    equal cells x itemsize (exact) and quant cells + scales (int8)."""
    fields = [(8, 6, 8), (9, 6, 8)]
    sch = schema_for_fields(0, fields, [1, 1], np.float64)
    assert sch.shapes == ((1, 6, 8), (1, 6, 8))
    assert sch.payload_bytes == 2 * 48 * 8
    q = schema_for_fields(0, fields, [1, 1], np.float64, WireFormat("int4"))
    assert q.payload_bytes == 2 * quant_slab_bytes(48, WireFormat("int4")) \
        + 2 * SCALE_BYTES
    assert q.wire_key == "int4" and str(q.wire_dtype) == "int8"


def test_schema_validates_slab_shapes():
    schema = slab_schema(0, [(1, 4, 4)], np.float32)
    with pytest.raises(InvalidArgumentError):
        schema.pack(_slabs([(1, 4, 5)], np.float32))
    with pytest.raises(InvalidArgumentError):
        schema.pack(_slabs([(1, 4, 4)] * 2, np.float32))
    with pytest.raises(InvalidArgumentError):
        slab_schema(0, [], np.float32)


# -- the full matrix (slow tier) --------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("tier", ["xla", "pallas"])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, "bfloat16"])
@pytest.mark.parametrize("dim", [0, 1, 2])
def test_exact_roundtrip_matrix(tier, dtype, dim):
    """Layout round-trip exactness for every state dtype on every exchange
    axis, both tiers (the Pallas pack covers dims 0/1; dim 2 and the flat
    layout stay XLA by design — `wire_pack_supported`)."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype == "bfloat16" else dtype
    shapes = [tuple(2 if d == dim else 8 for d in range(3))] * 3
    pallas = tier == "pallas"
    if pallas:
        from implicitglobalgrid_tpu.ops.pallas_halo import wire_pack_supported

        schema = slab_schema(dim, shapes, dtype)
        if not wire_pack_supported(schema.shapes, dim, schema.state_dtype):
            pytest.skip("pallas pack unsupported on this axis (by design)")
    _assert_exact_roundtrip(shapes, dtype, dim, pallas=pallas)


@pytest.mark.slow
@pytest.mark.parametrize("fmt_name", ["int8", "int4", "bfloat16"])
@pytest.mark.parametrize("state_dtype", [np.float32, np.float64])
def test_reduced_wire_roundtrip_matrix(fmt_name, state_dtype):
    """Quantized (int8/int4) and cast (bf16) formats: unpack returns the
    state dtype, byte accounting is exact, values match the per-slab
    reference codec (quant) or the cast round-trip (bf16)."""
    import jax.numpy as jnp

    fmt = WireFormat(fmt_name)
    shapes = [(2, 4, 8), (2, 4, 8), (2, 4, 8)]
    slabs = _slabs(shapes, state_dtype)
    schema = slab_schema(1, shapes, state_dtype, fmt)
    buf, back = _roundtrip(schema, slabs)
    assert int(buf.size) * int(buf.dtype.itemsize) == schema.payload_bytes
    for a, b in zip(slabs, back):
        assert b.dtype == a.dtype and b.shape == a.shape
        if fmt.is_quant:
            from implicitglobalgrid_tpu.ops.precision import (
                dequantize_slab, quantize_slab,
            )

            q, scale = quantize_slab(a.reshape(-1), fmt)
            ref = dequantize_slab(q, scale, a.size, fmt,
                                  a.dtype).reshape(a.shape)
        else:
            ref = a.astype(jnp.bfloat16).astype(a.dtype)
        assert np.array_equal(np.asarray(ref), np.asarray(b))
