"""Model integration tests: the distributed diffusion run must reproduce the
single-device run bit-for-bit on the interior — the TPU analog of the
reference verifying distributed semantics against the implicit global grid
(SURVEY.md §7 stage 4 acceptance)."""

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    init_diffusion3d, init_diffusion2d, make_step, run_diffusion,
)


def _run(nx, ny, nz, dims, nt, ndim=3, periods=(0, 0, 0)):
    igg.init_global_grid(nx, ny, nz, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    if ndim == 3:
        T, Cp, p = init_diffusion3d(dtype=np.float64)
    else:
        T, Cp, p = init_diffusion2d(dtype=np.float64)
    T = run_diffusion(T, Cp, p, nt, nt_chunk=7)
    out = igg.gather_interior(T)
    igg.finalize_global_grid()
    return out


def test_diffusion3d_distributed_matches_single_device():
    # 2x2x2 x local 6³ → global 10³; single device must use nx=10 for the
    # same implicit global grid: 1*(10-2)+2 = 10.
    multi = _run(6, 6, 6, (2, 2, 2), nt=20)
    single = _run(10, 10, 10, (1, 1, 1), nt=20)
    assert multi.shape == single.shape == (10, 10, 10)
    assert np.allclose(multi, single, rtol=0, atol=1e-12)
    # the diffusion actually did something
    assert not np.allclose(multi, _run(6, 6, 6, (2, 2, 2), nt=0))


def test_diffusion3d_periodic_consistency():
    multi = _run(6, 6, 6, (2, 2, 2), nt=10, periods=(1, 1, 1))
    single = _run(10, 10, 10, (1, 1, 1), nt=10, periods=(1, 1, 1))
    # periodic: global size = dims*(n-ol): 8 vs 8
    assert multi.shape == single.shape == (8, 8, 8)
    assert np.allclose(multi, single, rtol=0, atol=1e-12)


def test_diffusion2d_distributed_matches_single_device():
    multi = _run(6, 6, 1, (4, 2, 0), nt=15, ndim=2)
    single = _run(18, 10, 1, (1, 1, 0), nt=15, ndim=2)
    assert multi.shape == single.shape == (18, 10)
    assert np.allclose(multi, single, rtol=0, atol=1e-12)


def test_make_step_equals_run():
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    step = make_step(p)
    T1 = step(step(step(T, Cp), Cp), Cp)
    T2 = run_diffusion(T, Cp, p, 3, nt_chunk=3)
    assert np.allclose(np.asarray(T1), np.asarray(T2), rtol=0, atol=0)


def test_energy_conservation_periodic():
    # fully periodic diffusion conserves total energy (sum over implicit grid)
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    cp = igg.gather_interior(Cp)
    e0 = (cp * igg.gather_interior(T)).sum()
    T = run_diffusion(T, Cp, p, 25, nt_chunk=25)
    e1 = (cp * igg.gather_interior(T)).sum()
    assert abs(e1 - e0) / abs(e0) < 1e-12
