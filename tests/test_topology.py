"""Tests of the topology math: dims_create (MPI_Dims_create analog),
Cartesian ranks/coords, neighbor tables with PROC_NULL/periodic wrap
(reference `init_global_grid.jl:98-106`)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.parallel.topology import (
    PROC_NULL, cart_coords, cart_rank, cart_shift, dims_create, neighbors_table,
)
from implicitglobalgrid_tpu.utils.exceptions import IncoherentArgumentError


def test_dims_create_balanced():
    assert list(dims_create(8, (0, 0, 0))) == [2, 2, 2]
    assert list(dims_create(12, (0, 0, 0))) == [3, 2, 2]
    assert list(dims_create(6, (0, 0, 0))) == [3, 2, 1] or \
           list(dims_create(6, (0, 0, 0))) == [2, 3, 1]  # non-increasing preferred
    assert list(dims_create(7, (0, 0, 0))) == [7, 1, 1]
    assert list(dims_create(1, (0, 0, 0))) == [1, 1, 1]


def test_dims_create_fixed_entries():
    assert list(dims_create(8, (2, 0, 0))) == [2, 2, 2]
    assert list(dims_create(8, (4, 0, 0))) == [4, 2, 1]
    assert list(dims_create(8, (0, 8, 0))) == [1, 8, 1]
    with pytest.raises(IncoherentArgumentError):
        dims_create(8, (3, 0, 0))  # 8 not divisible by 3
    with pytest.raises(IncoherentArgumentError):
        dims_create(8, (2, 2, 3))  # fully fixed but prod != nprocs


def test_dims_create_non_increasing():
    d = dims_create(24, (0, 0, 0))
    assert int(np.prod(d)) == 24
    assert list(d) == sorted(d, reverse=True)  # MPI spec: non-increasing


def test_cart_rank_roundtrip():
    dims = (3, 4, 5)
    for r in range(3 * 4 * 5):
        assert cart_rank(cart_coords(r, dims), dims) == r


def test_cart_shift_interior_and_edges():
    dims, periods = (3, 1, 1), (0, 0, 0)
    left, right = cart_shift((1, 0, 0), 0, 1, dims, periods)
    assert left == cart_rank((0, 0, 0), dims) and right == cart_rank((2, 0, 0), dims)
    left, right = cart_shift((0, 0, 0), 0, 1, dims, periods)
    assert left == PROC_NULL
    left, right = cart_shift((2, 0, 0), 0, 1, dims, periods)
    assert right == PROC_NULL


def test_cart_shift_periodic_wrap():
    dims, periods = (3, 1, 1), (1, 0, 0)
    left, right = cart_shift((0, 0, 0), 0, 1, dims, periods)
    assert left == cart_rank((2, 0, 0), dims) and right == cart_rank((1, 0, 0), dims)
    # self-neighbor: periodic with a single shard (reference update_halo.jl:62)
    left, right = cart_shift((0, 0, 0), 1, 1, (3, 1, 1), (0, 1, 0))
    assert left == right == cart_rank((0, 0, 0), (3, 1, 1))


def test_neighbors_table_against_grid():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periodz=1, quiet=True)
    tbl = neighbors_table((0, 0, 0))
    assert tbl[0, 0] == PROC_NULL          # no left-x neighbor at coord 0
    assert tbl[1, 0] == cart_rank((1, 0, 0), (2, 2, 2))
    assert tbl[0, 2] == cart_rank((0, 0, 1), (2, 2, 2))  # periodic z wraps
    assert tbl.shape == (2, 3)


def test_ol_staggered():
    # ol(dim, A) = overlaps[dim] + (size(A,dim) - nxyz[dim])  (shared.jl:107)
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    assert igg.ol(0) == 2
    assert igg.ol(0, (6, 5, 5)) == 3
    assert igg.ol(1, (5, 4, 5)) == 1
