"""Observability subsystem tests (ISSUE 3): metrics registry (incl.
thread-safety under concurrent `on_report`-style writers), flight-recorder
JSONL round-trip, Prometheus exposition parsing, static halo comm
accounting, the unified run report reconstructing a fault-injected run
from the JSONL alone, and the PR's satellite fixes."""

import json
import os
import re
import threading

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import telemetry
from implicitglobalgrid_tpu.telemetry.registry import MetricsRegistry
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """No recorder or metric series leaks between tests (family
    registrations survive by design — handles stay valid)."""
    igg.stop_flight_recorder()
    igg.reset_metrics()
    yield
    igg.stop_flight_recorder()
    igg.reset_metrics()


def _init(dimx=2, dimy=2, dimz=1):
    igg.init_global_grid(6, 6, 6, dimx=dimx, dimy=dimy, dimz=dimz,
                         quiet=True)


def _diffusion_step():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float64)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "a counter", ("kind",))
    c.inc(1, kind="a")
    c.inc(2.5, kind="a")
    c.inc(1, kind="b")
    assert c.value(kind="a") == 3.5 and c.value(kind="b") == 1
    g = reg.gauge("g", "a gauge")
    g.set(7)
    g.add(-2)
    assert g.value() == 5
    h = reg.histogram("h_seconds", "a histogram", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 100.0):
        h.observe(v)
    ((labels, st),) = h.samples()
    assert labels == {} and st["count"] == 4
    assert st["counts"] == [1, 2, 0, 1]  # <=0.1, <=1, <=10, +Inf
    assert abs(st["sum"] - 101.05) < 1e-9


def test_registry_registration_conflicts_and_validation():
    reg = MetricsRegistry()
    c = reg.counter("x_total", "h", ("a",))
    assert reg.counter("x_total", "h", ("a",)) is c  # idempotent
    with pytest.raises(InvalidArgumentError, match="already registered"):
        reg.gauge("x_total")
    with pytest.raises(InvalidArgumentError, match="already registered"):
        reg.counter("x_total", "h", ("b",))
    with pytest.raises(InvalidArgumentError, match="Invalid metric name"):
        reg.counter("bad name")
    with pytest.raises(InvalidArgumentError, match="Invalid label name"):
        reg.counter("ok_total", "h", ("bad-label",))
    with pytest.raises(InvalidArgumentError, match="takes labels"):
        c.inc(1, wrong="z")
    with pytest.raises(InvalidArgumentError, match="cannot decrease"):
        c.inc(-1, a="z")
    with pytest.raises(InvalidArgumentError, match="strictly increasing"):
        reg.histogram("h2", "h", buckets=(1.0, 1.0))


def test_registry_thread_safety():
    """The driver's `on_report` callbacks may record from user threads:
    concurrent counter/histogram writes (plus a snapshotting reader) must
    never lose an increment or crash."""
    reg = MetricsRegistry()
    c = reg.counter("threads_total", "t", ("worker",))
    h = reg.histogram("threads_seconds", "t", buckets=(0.5, 1.0))
    n_threads, n_iter = 8, 2000
    errs = []

    def writer(w):
        try:
            for i in range(n_iter):
                c.inc(1, worker=str(w % 4))
                h.observe((i % 3) * 0.4)
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    def reader():
        try:
            for _ in range(200):
                telemetry.prometheus_snapshot(reg)
                reg.collect()
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_threads)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert sum(v for _, v in c.samples()) == n_threads * n_iter
    total = sum(st["count"] for _, st in h.samples())
    assert total == n_threads * n_iter


def test_health_events_family_in_registry():
    """The resilient runtime's health events are the
    `igg_health_events_total{kind=...}` counter family — the registry is
    the ONLY API (the PR-2 `health_counters`/`record_health_event`/
    `reset_health_counters` shims were retired after two majors of
    deprecation notice); a family reset leaves other metric families
    untouched."""
    from implicitglobalgrid_tpu.telemetry.hooks import record_health_event

    assert not hasattr(igg, "health_counters")  # shim retired
    record_health_event("chunks")
    record_health_event("chunks", 2)
    record_health_event("rollbacks")
    fam = igg.metrics_registry().get("igg_health_events_total")
    assert fam is not None and fam.value(kind="chunks") == 3
    assert fam.value(kind="rollbacks") == 1
    other = igg.metrics_registry().counter("unrelated_total", "x")
    other.inc(5)
    igg.metrics_registry().reset("igg_health_events_total")
    fam = igg.metrics_registry().get("igg_health_events_total")
    assert fam is None or not list(fam.samples())
    assert other.value() == 5
    snap = telemetry.prometheus_snapshot()
    assert "unrelated_total 5" in snap


# ---------------------------------------------------------------------------
# Prometheus exposition format
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? "
    r"([+-]?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|Inf|NaN))$")
_LABEL_ITEM_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def test_prometheus_snapshot_parses_with_escaped_labels():
    reg = MetricsRegistry()
    nasty = 'he said "hi"\\there\nnewline'
    reg.counter("esc_total", "counts\nwith newline help", ("who",)).inc(
        4, who=nasty)
    reg.gauge("level", "plain").set(2.5)
    reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)).observe(0.3)
    text = telemetry.prometheus_snapshot(reg)
    assert text.endswith("\n")
    helps, types, samples = {}, {}, []
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name, help_text = line[len("# HELP "):].split(" ", 1)
            helps[name] = help_text
            assert "\n" not in help_text
            continue
        if line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ")
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        samples.append(m.groups())
    assert types == {"esc_total": "counter", "level": "gauge",
                     "lat_seconds": "histogram"}
    # every sample belongs to a declared family (histogram suffixes too)
    for name, labels, _ in samples:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in types or base in types, name
        for k, v in _LABEL_ITEM_RE.findall(labels or ""):
            if k == "who":  # the escaped value round-trips
                unescaped = (v.replace("\\\\", "\0").replace('\\"', '"')
                             .replace("\\n", "\n").replace("\0", "\\"))
                assert unescaped == nasty
    # histogram semantics: cumulative buckets, +Inf == _count
    hist = {n: float(v) for n, l, v in samples if n.startswith("lat_")}
    by_le = [(l, float(v)) for n, l, v in samples
             if n == "lat_seconds_bucket"]
    cum = [v for _, v in by_le]
    assert cum == sorted(cum) and cum[-1] == hist["lat_seconds_count"] == 1
    assert abs(hist["lat_seconds_sum"] - 0.3) < 1e-9


def test_prometheus_snapshot_golden_label_escaping():
    """Exposition-format edge cases locked against GOLDEN strings: label
    values containing ``"`` / newline / backslash must escape exactly as
    the format spec says (backslash first — a quote escaped after a
    backslash double-escapes)."""
    reg = MetricsRegistry()
    c = reg.counter("edge_total", "h", ("v",))
    c.inc(1, v='quote"end')
    c.inc(2, v="line\nbreak")
    c.inc(3, v="back\\slash")
    c.inc(4, v='all\\"of\nit')
    text = telemetry.prometheus_snapshot(reg)
    assert 'edge_total{v="quote\\"end"} 1' in text
    assert 'edge_total{v="line\\nbreak"} 2' in text
    assert 'edge_total{v="back\\\\slash"} 3' in text
    assert 'edge_total{v="all\\\\\\"of\\nit"} 4' in text


def test_prometheus_snapshot_golden_inf_nan_gauges():
    """±Inf and NaN gauge samples render as the spec's literal tokens
    (``+Inf`` / ``-Inf`` / ``NaN``), never as python's ``inf``/``nan``."""
    reg = MetricsRegistry()
    g = reg.gauge("extreme", "h", ("which",))
    g.set(float("inf"), which="pos")
    g.set(float("-inf"), which="neg")
    g.set(float("nan"), which="nan")
    g.set(-0.0, which="negzero")
    text = telemetry.prometheus_snapshot(reg)
    assert 'extreme{which="pos"} +Inf' in text
    assert 'extreme{which="neg"} -Inf' in text
    assert 'extreme{which="nan"} NaN' in text
    assert 'extreme{which="negzero"} 0' in text
    assert "inf\n" not in text and "nan\n" not in text


def test_prometheus_snapshot_empty_registry_golden():
    reg = MetricsRegistry()
    assert telemetry.prometheus_snapshot(reg) == ""
    # a registered family with no series still exposes HELP/TYPE
    reg.counter("lonely_total", "no samples yet")
    assert telemetry.prometheus_snapshot(reg) == (
        "# HELP lonely_total no samples yet\n"
        "# TYPE lonely_total counter\n")


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_roundtrip(tmp_path):
    """write -> read -> report: records carry monotonic timestamps, run id,
    pid, process index, and a per-recorder sequence number."""
    p = str(tmp_path / "run.jsonl")
    rec = igg.start_flight_recorder(p, run_id="r1")
    igg.record_event("alpha", x=1, arr=np.int64(7), frac=np.float32(0.33))
    with igg.record_span("beta", label="timed"):
        pass
    rec.event("gamma")
    path = igg.stop_flight_recorder()
    assert path == p
    evs = igg.read_flight_events(path)
    kinds = [e["kind"] for e in evs]
    assert kinds == ["recorder_open", "alpha", "beta", "gamma",
                     "recorder_close"]
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts)
    assert [e["seq"] for e in evs] == list(range(len(evs)))
    assert all(e["run"] == "r1" and e["pid"] == os.getpid()
               and "proc" in e for e in evs)
    assert evs[1]["x"] == 1 and evs[1]["arr"] == 7  # numpy serialized
    assert abs(evs[1]["frac"] - 0.33) < 1e-6  # np floats NOT int-truncated
    assert evs[2]["dur_s"] >= 0 and evs[2]["label"] == "timed"
    assert evs[0]["wall"] > 0  # wall-clock anchor for the monotonic ts


def test_record_event_is_noop_without_recorder(tmp_path):
    assert igg.flight_recorder() is None
    igg.record_event("nothing", x=1)  # must not raise or create files
    with igg.record_span("nothing_timed"):
        pass
    assert list(tmp_path.iterdir()) == []
    assert igg.stop_flight_recorder() is None


def test_read_tolerates_torn_final_line_only(tmp_path):
    p = tmp_path / "torn.jsonl"
    p.write_text(json.dumps({"kind": "a", "run": "r"}) + "\n"
                 + '{"kind": "b", "run')  # crash mid-write
    evs = igg.read_flight_events(str(p))
    assert [e["kind"] for e in evs] == ["a"]
    p2 = tmp_path / "corrupt.jsonl"
    p2.write_text('garbage\n' + json.dumps({"kind": "a"}) + "\n")
    with pytest.raises(InvalidArgumentError, match="interior"):
        igg.read_flight_events(str(p2))
    with pytest.raises(InvalidArgumentError, match="not found"):
        igg.read_flight_events(str(tmp_path / "missing.jsonl"))


def test_failed_recorder_open_keeps_active_recorder(tmp_path):
    """start_flight_recorder with an unopenable path must raise WITHOUT
    killing the currently-active recorder."""
    r = igg.start_flight_recorder(str(tmp_path / "ok.jsonl"), run_id="keep")
    with pytest.raises(OSError):
        igg.start_flight_recorder(str(tmp_path / "no" / "such" / "x.jsonl"))
    assert igg.flight_recorder() is r
    igg.record_event("still_alive")
    path = igg.stop_flight_recorder()
    assert any(e["kind"] == "still_alive"
               for e in igg.read_flight_events(path))


def test_recorder_thread_safety(tmp_path):
    """Concurrent writers (driver thread + on_report user threads) produce
    a valid JSONL stream with unique, gapless sequence numbers."""
    igg.start_flight_recorder(str(tmp_path / "mt.jsonl"), run_id="mt")
    n_threads, n_iter = 6, 300

    def writer(w):
        for i in range(n_iter):
            igg.record_event("w", worker=w, i=i)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    path = igg.stop_flight_recorder()
    evs = igg.read_flight_events(path)
    ws = [e for e in evs if e["kind"] == "w"]
    assert len(ws) == n_threads * n_iter
    seqs = [e["seq"] for e in evs]
    assert sorted(seqs) == list(range(len(evs)))  # unique and gapless


def test_recorder_into_directory_and_multi_run_filter(tmp_path):
    """A directory path follows the per-process convention
    (``flight_p<process_index>.jsonl``) so N controllers sharing one
    directory never interleave one file — the layout `aggregate_flight`
    globs (single-process tests run as process 0)."""
    igg.start_flight_recorder(str(tmp_path), run_id="runA")
    igg.record_event("a")
    path = igg.stop_flight_recorder()
    assert os.path.basename(path) == "flight_p0.jsonl"
    # second run appended into the SAME file still separates by run id
    igg.start_flight_recorder(path, run_id="runB")
    igg.record_event("b")
    igg.stop_flight_recorder()
    assert {e["run"] for e in igg.read_flight_events(path)} == \
        {"runA", "runB"}
    only_b = igg.read_flight_events(path, run_id="runB")
    assert {e["run"] for e in only_b} == {"runB"}
    rep = igg.run_report(path, include_metrics=False)
    assert rep["run_id"] == "runB"  # default: the LAST run in the file
    rep_a = igg.run_report(path, run_id="runA", include_metrics=False)
    assert rep_a["run_id"] == "runA"
    with pytest.raises(InvalidArgumentError, match="not present"):
        igg.run_report(path, run_id="nope")


# ---------------------------------------------------------------------------
# Static halo comm accounting
# ---------------------------------------------------------------------------

def test_halo_comm_plan_bytes_and_collectives():
    """2x2x2 fully periodic, hw=1, local 6^3 f64 blocks: per axis one
    ppermute pair whose per-shard payload is a 36-cell slab; bytes sum the
    payload over every link (2 shards send per direction)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T = igg.ones_g(dtype=np.float32)
    plan = igg.halo_comm_plan(T)
    slab = 6 * 6 * 1 * 4                      # cells x f32
    per_axis = slab * (2 + 2)                 # 2 links per direction
    assert plan["ppermutes"] == 6
    assert plan["wire_bytes"] == 3 * per_axis
    assert plan["local_copy_bytes"] == 0
    assert set(plan["axes"]) == {"gx", "gy", "gz"}
    assert all(r["by_dtype"] == {"float32": per_axis}
               for r in plan["axes"].values())

    # coalescing: 2 fields -> same ppermute count, bytes double; per-field
    # path doubles the collectives instead (bytes invariant)
    B = igg.ones_g(dtype=np.float32)
    plan2 = igg.halo_comm_plan(T, B)
    assert plan2["ppermutes"] == 6
    assert plan2["wire_bytes"] == 2 * plan["wire_bytes"]
    plan2pf = igg.halo_comm_plan(T, B, coalesce=False)
    assert plan2pf["ppermutes"] == 12
    assert plan2pf["wire_bytes"] == plan2["wire_bytes"]

    # wire precision: f32 payloads ship as bf16 -> bytes halve
    planw = igg.halo_comm_plan(T, B, wire_dtype="bfloat16")
    assert planw["wire_bytes"] == plan2["wire_bytes"] // 2
    assert all(set(r["by_dtype"]) == {"bfloat16"}
               for r in planw["axes"].values())


def test_halo_comm_plan_self_neighbor_and_nonperiodic():
    # all-self periodic grid: no collectives, only local slab swaps
    igg.init_global_grid(6, 6, 6, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T = igg.ones_g(dtype=np.float32)
    plan = igg.halo_comm_plan(T)
    assert plan["ppermutes"] == 0 and plan["wire_bytes"] == 0
    assert plan["local_copy_bytes"] == 3 * 2 * (6 * 6 * 4)
    igg.finalize_global_grid()
    # non-periodic 4x1x1: truncated chains -> 3 links per direction
    igg.init_global_grid(6, 6, 6, dimx=4, dimy=1, dimz=1, quiet=True)
    T = igg.ones_g(dtype=np.float32)
    plan = igg.halo_comm_plan(T)
    assert plan["ppermutes"] == 2
    assert plan["wire_bytes"] == (6 * 6 * 4) * (3 + 3)


def test_update_halo_charges_plan_to_registry():
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T = igg.ones_g(dtype=np.float32)
    plan = igg.halo_comm_plan(T)
    reg = igg.metrics_registry()
    base = reg.counter("igg_halo_exchanges_total").value()
    T = igg.update_halo(T)
    T = igg.update_halo(T)
    assert reg.counter("igg_halo_exchanges_total").value() == base + 2
    fam = reg.get("igg_halo_wire_bytes_total")
    total = sum(v for _, v in fam.samples())
    assert total == 2 * plan["wire_bytes"]
    fam_p = reg.get("igg_halo_ppermutes_total")
    assert sum(v for _, v in fam_p.samples()) == 2 * plan["ppermutes"]


# ---------------------------------------------------------------------------
# The unified run report (acceptance: reconstruct a fault-injected run
# from the JSONL alone)
# ---------------------------------------------------------------------------

def test_run_report_reconstructs_fault_injected_run(tmp_path):
    _init()
    step, state = _diffusion_step()
    igg.start_flight_recorder(str(tmp_path / "run.jsonl"), run_id="faulty")
    out, reports = igg.run_resilient(
        step, state, 20, nt_chunk=5, key="tel_fault",
        checkpoint_dir=str(tmp_path / "ck"),
        faults=[igg.NaNPoke(step=12, name="T", index=(0, 0, 0))])
    path = igg.stop_flight_recorder()

    # the report is built from the FILE alone (a fresh process could do it)
    rep = igg.run_report(path, include_metrics=False)
    assert rep["run_id"] == "faulty"
    assert rep["steps"] == {"nt": 20, "completed": 20}
    assert rep["chunks"]["count"] == len(reports)
    assert rep["chunks"]["tripped"] == 1
    assert rep["guards"] == {"trips": 1, "reasons": {"nonfinite:T": 1}}
    assert rep["checkpoints"]["rollbacks"] == 1
    assert rep["checkpoints"]["restores"] == 1
    assert rep["checkpoints"]["saves"] >= 3
    assert rep["checkpoints"]["save_s_total"] > 0
    assert rep["chunks"]["exec_s_total"] > 0
    assert rep["runner_cache"]["misses"] >= 1  # compiles attributed
    assert rep["chunks"]["cold"] == rep["runner_cache"]["misses"]

    # full event sequence, in order: the tripped chunk at the injection
    # step, then restore -> rollback, then the recomputed chunks
    kinds = [e["kind"] for e in rep["sequence"]]
    assert kinds[0] == "run_begin" and kinds[-1] == "run_end"
    i_fault = kinds.index("fault_injected")
    i_trip = kinds.index("guard_trip")
    i_restore = kinds.index("checkpoint_restore")
    i_roll = kinds.index("rollback")
    assert i_fault < i_trip < i_restore < i_roll < len(kinds) - 1
    tripped = [e for e in rep["sequence"]
               if e["kind"] == "chunk" and not e["ok"]]
    assert len(tripped) == 1 and tripped[0]["step_begin"] == 12
    roll = next(e for e in rep["sequence"] if e["kind"] == "rollback")
    assert roll["to_step"] == 10 and roll["fallback"] is False
    # chunk boundaries replay the driver's schedule exactly
    spans = [(e["step_begin"], e["step_end"]) for e in rep["sequence"]
             if e["kind"] == "chunk"]
    assert spans[0] == (0, 5) and (10, 12) in spans and spans[-1] == (15, 20)


def test_run_report_merges_trace_and_metrics(tmp_path):
    """`run_report` is the single pane: flight log + registry snapshot +
    profiler capture analysis in one structured record."""
    _init()
    step, state = _diffusion_step()
    igg.start_flight_recorder(str(tmp_path / "run.jsonl"))
    with igg.trace(str(tmp_path / "trace")):
        out, _ = igg.run_resilient(step, state, 4, nt_chunk=2,
                                   key="tel_trace")
    path = igg.stop_flight_recorder()
    rep = igg.run_report(path, trace_dir=str(tmp_path / "trace"))
    assert "overlap_stats" in rep and "op_breakdown" in rep
    assert isinstance(rep["op_breakdown"], list)
    names = {fam["name"] for fam in rep["metrics"]}
    assert "igg_health_events_total" in names
    assert "igg_runner_cache_total" in names


def test_run_report_sequence_carries_snapshot_writer_close(tmp_path):
    """Regression: the driver emits ``snapshot_writer_close`` with the
    writer's drain stats on every exit path, but the kind was missing
    from `_SEQ_FIELDS` — the stats silently vanished from the
    reconstructed sequence. They must survive, fields included."""
    igg.start_flight_recorder(str(tmp_path / "run.jsonl"), run_id="wc")
    igg.record_event("run_begin", nt=10, nt_chunk=5, names=["T"])
    igg.record_event("chunk", chunk=0, step_begin=0, step_end=10, ok=True,
                     reasons=[], build_s=0.01, exec_s=0.1)
    igg.record_event("snapshot_writer_close", submitted=3, written=2,
                     staged=0, dropped=1, errors=0, bytes=4096)
    igg.record_event("run_end", completed=10, chunks=1)
    path = igg.stop_flight_recorder()
    rep = igg.run_report(path, include_metrics=False)
    kinds = [e["kind"] for e in rep["sequence"]]
    assert "snapshot_writer_close" in kinds
    close = next(e for e in rep["sequence"]
                 if e["kind"] == "snapshot_writer_close")
    assert close == {"kind": "snapshot_writer_close", "t": close["t"],
                     "submitted": 3, "written": 2, "staged": 0,
                     "dropped": 1, "errors": 0, "bytes": 4096}
    # end-to-end: a real snapshotting run's drain stats reach the sequence
    _init()
    step, state = _diffusion_step()
    igg.start_flight_recorder(str(tmp_path / "run2.jsonl"), run_id="wc2")
    igg.run_resilient(step, state, 4, nt_chunk=2, key="tel_wc",
                      snapshot_dir=str(tmp_path / "snaps"))
    rep2 = igg.run_report(igg.stop_flight_recorder(),
                          include_metrics=False)
    close2 = [e for e in rep2["sequence"]
              if e["kind"] == "snapshot_writer_close"]
    assert len(close2) == 1 and close2[0]["written"] == 2
    assert close2[0]["submitted"] == 2 and "bytes" in close2[0]


def test_report_cli_subprocess(tmp_path):
    """The operator entry point: `python -m implicitglobalgrid_tpu.tools
    report run.jsonl` prints the JSON report post-hoc."""
    import subprocess
    import sys

    igg.start_flight_recorder(str(tmp_path / "run.jsonl"), run_id="cli")
    igg.record_event("run_begin", nt=10)
    igg.record_event("chunk", chunk=0, step_begin=0, step_end=10, ok=True,
                     exec_s=0.1)
    igg.record_event("run_end", completed=10, chunks=1)
    path = igg.stop_flight_recorder()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "implicitglobalgrid_tpu.tools", "report",
         path, "--no-metrics"],
        capture_output=True, text=True, timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rep = json.loads(proc.stdout)
    assert rep["run_id"] == "cli"
    assert rep["steps"] == {"nt": 10, "completed": 10}
    assert rep["chunks"]["count"] == 1 and "metrics" not in rep


# ---------------------------------------------------------------------------
# Satellite: toc() before tic() raises the typed error
# ---------------------------------------------------------------------------

def test_toc_without_tic_raises_typed_error(monkeypatch):
    from implicitglobalgrid_tpu.utils import timing

    _init()
    monkeypatch.setattr(timing, "_t0", None)
    with pytest.raises(InvalidArgumentError, match="tic"):
        igg.toc()
    igg.tic()
    assert igg.toc() >= 0.0


def test_finalize_resets_chronometer():
    from implicitglobalgrid_tpu.utils import timing

    _init()
    igg.tic()
    igg.finalize_global_grid()
    assert timing._t0 is None


# ---------------------------------------------------------------------------
# Distributed tracing (ISSUE 20): TraceContext, recorder stamping, OTLP
# ---------------------------------------------------------------------------

def test_trace_context_parse_format_child_fields():
    """The W3C traceparent round trip: mint, render, parse, derive."""
    from implicitglobalgrid_tpu.telemetry import TraceContext

    root = TraceContext.new()
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    assert root.parent_span_id is None and root.flags == "01"

    hdr = root.to_traceparent()
    assert re.fullmatch(
        rf"00-{root.trace_id}-{root.span_id}-01", hdr)
    back = TraceContext.parse(hdr)
    assert back.trace_id == root.trace_id
    assert back.span_id == root.span_id

    # whitespace / case are normalized on parse
    assert TraceContext.parse("  " + hdr.upper() + " ").span_id \
        == root.span_id

    kid = root.child()
    assert kid.trace_id == root.trace_id
    assert kid.parent_span_id == root.span_id
    assert kid.span_id != root.span_id
    assert kid.fields() == {"trace_id": root.trace_id,
                            "span_id": kid.span_id,
                            "parent_span_id": root.span_id}
    assert root.fields() == {"trace_id": root.trace_id,
                             "span_id": root.span_id}


def test_trace_context_rejects_malformed():
    from implicitglobalgrid_tpu.telemetry import TraceContext

    good = TraceContext.new().to_traceparent()
    for bad in ("", "nonsense", good[:-3],              # truncated
                "ff" + good[2:],                        # reserved version
                "00-" + "0" * 32 + good[35:],           # all-zero trace
                good[:36] + "0" * 16 + good[52:],       # all-zero span
                good.replace("-", "_")):
        with pytest.raises(InvalidArgumentError):
            TraceContext.parse(bad)
    with pytest.raises(InvalidArgumentError):
        TraceContext.parse(None)
    with pytest.raises(InvalidArgumentError):
        TraceContext(trace_id="xyz")
    with pytest.raises(InvalidArgumentError):
        TraceContext(trace_id="a" * 32, span_id="0" * 16)


def test_flight_recorder_trace_stamping_off_is_byte_identical(tmp_path):
    """THE zero-regression claim: an untraced recorder writes records
    with NO trace keys at all (grep-level identical schema to every
    prior release), and a traced one differs ONLY by the two stamp
    keys — `recorder_open` stays untraced either way (it is emitted
    before `.trace` can be assigned), proving the file header schema
    never moved."""
    from implicitglobalgrid_tpu.telemetry import (
        FlightRecorder, TraceContext, read_flight_events,
    )

    def drive(rec):
        rec.event("run_begin", nt=8)
        rec.event("chunk", chunk=0, step_begin=0, step_end=4, ok=True,
                  exec_s=0.25, build_s=0.5, n=4)
        rec.event("guard_trip", chunk=0, reason="nonfinite")
        rec.close()

    p_off = tmp_path / "off.jsonl"
    rec = FlightRecorder(str(p_off), run_id="tr_off")
    drive(rec)
    raw = p_off.read_text()
    assert "trace_id" not in raw and "span_id" not in raw

    tr = TraceContext.new().child()  # job root span, as the scheduler sets
    p_on = tmp_path / "on.jsonl"
    rec = FlightRecorder(str(p_on), run_id="tr_on")
    rec.trace = tr
    drive(rec)

    off = read_flight_events(str(p_off))
    on = read_flight_events(str(p_on))
    assert [e["kind"] for e in off] == [e["kind"] for e in on]
    for e_off, e_on in zip(off, on):
        if e_on["kind"] == "recorder_open":
            assert "trace_id" not in e_on  # pre-assignment: never traced
            extra = set()
        else:
            assert e_on["trace_id"] == tr.trace_id
            assert e_on["parent_span_id"] == tr.span_id
            assert "span_id" not in e_on  # ids synthesized at export only
            extra = {"trace_id", "parent_span_id"}
        # the ONLY schema delta is the stamp itself
        assert set(e_on) - set(e_off) == extra


_TID = "0af7651916cd43dd8448eb211c80319c"
_API = "b7ad6b7169203331"   # the serve tier's span (dangling parent)
_ROOT = "00f067aa0ba902b7"  # job_claimed: the job's root span


def _golden_trace_dir(tmp_path):
    """Hand-written journal + flight streams of ONE traced job: the
    deterministic fixture the OTLP goldens (and the trace CLI) decode."""
    tid = _TID

    def w(path, evs):
        with open(path, "w", encoding="utf-8") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")

    w(tmp_path / "journal.jsonl", [
        {"kind": "recorder_open", "wall": 2000.0, "t": 100.0,
         "run": "scheduler", "pid": 1, "proc": 0, "seq": 0},
        {"kind": "job_claimed", "t": 101.0, "run": "scheduler",
         "job": "j1", "owner": "sched-1", "trace_id": tid,
         "span_id": _ROOT, "parent_span_id": _API,
         "pid": 1, "proc": 0, "seq": 1},
        {"kind": "admission_priced", "t": 102.0, "run": "scheduler",
         "job": "j1", "price": 3, "trace_id": tid,
         "span_id": "1111111111111111", "parent_span_id": _ROOT,
         "pid": 1, "proc": 0, "seq": 2},
        {"kind": "alert", "t": 103.0, "run": "scheduler", "job": "j1",
         "rule": "deadline_slack_burn", "state": "firing",
         "trace_id": tid, "span_id": "2222222222222222",
         "parent_span_id": _ROOT, "pid": 1, "proc": 0, "seq": 3},
        {"kind": "autoscale_decision", "t": 103.5, "run": "scheduler",
         "job": "j1", "verdict": "grow", "trace_id": tid,
         "span_id": "3333333333333333", "parent_span_id": _ROOT,
         "pid": 1, "proc": 0, "seq": 4},
        {"kind": "resize_requested", "t": 104.0, "run": "scheduler",
         "job": "j1", "new_dims": [2, 2, 1], "trace_id": tid,
         "span_id": "4444444444444444", "parent_span_id": _ROOT,
         "pid": 1, "proc": 0, "seq": 5},
        # a DIFFERENT job on the same journal: the job= filter's foil
        {"kind": "job_claimed", "t": 105.0, "run": "scheduler",
         "job": "other", "trace_id": "beef" * 8,
         "span_id": "5555555555555555",
         "pid": 1, "proc": 0, "seq": 6},
    ])
    w(tmp_path / "job_j1.jsonl", [
        {"kind": "recorder_open", "wall": 1910.0, "t": 10.0,
         "run": "j1", "pid": 2, "proc": 0, "seq": 0},
        {"kind": "chunk", "t": 11.5, "run": "j1", "chunk": 0, "n": 4,
         "exec_s": 1.0, "build_s": 0.5, "ok": True, "trace_id": tid,
         "parent_span_id": _ROOT, "pid": 2, "proc": 0, "seq": 1},
        {"kind": "guard_trip", "t": 11.75, "run": "j1", "chunk": 0,
         "reason": "nonfinite", "trace_id": tid,
         "parent_span_id": _ROOT, "pid": 2, "proc": 0, "seq": 2},
        {"kind": "resize", "t": 12.0, "run": "j1", "dur_s": 0.25,
         "new_dims": [2, 2, 1], "via": "disk", "trace_id": tid,
         "parent_span_id": _ROOT, "pid": 2, "proc": 0, "seq": 3},
        # untraced events vanish from the OTLP view entirely
        {"kind": "run_end", "t": 12.5, "run": "j1", "completed": 8,
         "pid": 2, "proc": 0, "seq": 4},
    ])
    return tmp_path


def _all_spans(doc):
    return [s for rs in doc["resourceSpans"]
            for ss in rs["scopeSpans"] for s in ss["spans"]]


def test_export_otlp_golden_span_tree(tmp_path):
    """The OTLP encoder golden: exact wall-anchored nanosecond windows,
    int64-as-string attributes, one resource per (run, proc), red-flag
    kinds pinned as span EVENTS on their parent, the resize link, and a
    parent-connected tree whose only dangling parent is the serve
    tier's span."""
    import hashlib

    from implicitglobalgrid_tpu.telemetry import export_otlp

    d = _golden_trace_dir(tmp_path)
    doc = export_otlp(str(d), trace_id=_TID)

    # resources: the scheduler journal and job j1's flight stream
    services = {}
    for rs in doc["resourceSpans"]:
        attrs = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
        services[attrs["igg.run"]["stringValue"]] = \
            attrs["service.name"]["stringValue"]
    assert services == {"scheduler": "igg-scheduler", "j1": "igg-job"}

    spans = _all_spans(doc)
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"job_claimed", "admission_priced", "alert",
                            "autoscale_decision", "resize_requested",
                            "chunk", "guard_trip", "resize"}
    assert all(s["traceId"] == _TID and s["kind"] == 1 for s in spans)

    # exactly one root — the span whose parent is NOT in the export: the
    # serve tier's request span, the one link out of the repo's streams
    ids = {s["spanId"] for s in spans}
    assert len(ids) == len(spans)
    roots = [s for s in spans if s.get("parentSpanId") not in ids]
    assert [s["name"] for s in roots] == ["job_claimed"]
    assert roots[0]["spanId"] == _ROOT
    assert roots[0]["parentSpanId"] == _API

    # wall-anchored windows: journal anchor 2000-100=1900, flight anchor
    # 1910-10=1900 — the chunk span backs off build+exec before its stamp
    chunk = by_name["chunk"]
    assert chunk["startTimeUnixNano"] == str(int(1910.0 * 1e9))
    assert chunk["endTimeUnixNano"] == str(int(1911.5 * 1e9))
    claimed = by_name["job_claimed"]
    assert claimed["startTimeUnixNano"] == claimed["endTimeUnixNano"] \
        == str(int(2001.0 * 1e9))
    rz = by_name["resize"]
    assert rz["startTimeUnixNano"] == str(int(1911.75 * 1e9))

    # flight spans get the deterministic export-time id
    want = hashlib.sha256(f"{_TID}:j1:0:1".encode()).hexdigest()[:16]
    assert chunk["spanId"] == want

    # attribute encoding: int64 as string, reserved keys dropped
    priced = {a["key"]: a["value"]
              for a in by_name["admission_priced"]["attributes"]}
    assert priced["price"] == {"intValue": "3"}
    assert priced["job"] == {"stringValue": "j1"}
    assert "t" not in priced and "trace_id" not in priced
    chunk_attrs = {a["key"]: a["value"] for a in chunk["attributes"]}
    assert chunk_attrs["ok"] == {"boolValue": True}
    assert chunk_attrs["exec_s"] == {"doubleValue": 1.0}

    # red-flag kinds double as span events on the job root
    ev_names = {e["name"] for e in claimed.get("events", ())}
    assert {"alert", "autoscale_decision", "guard_trip"} <= ev_names

    # the applied resize links back to the journal's resize_requested
    links = rz.get("links", [])
    assert len(links) == 1
    assert links[0]["spanId"] == by_name["resize_requested"]["spanId"]
    assert links[0]["attributes"] == [
        {"key": "igg.link", "value": {"stringValue": "resize_requested"}}]


def test_export_otlp_filters_and_errors(tmp_path):
    from implicitglobalgrid_tpu.telemetry import export_otlp

    d = _golden_trace_dir(tmp_path)

    # job= filter: the foreign job's claim drops out
    doc = export_otlp(str(d), job="j1")
    assert all(s["traceId"] == _TID for s in _all_spans(doc))
    # no filter: both traces present
    tids = {s["traceId"] for s in _all_spans(export_otlp(str(d)))}
    assert tids == {_TID, "beef" * 8}
    # unknown trace / empty dir are typed errors, and out= writes a file
    with pytest.raises(InvalidArgumentError):
        export_otlp(str(d), trace_id="c0de" * 8)
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(InvalidArgumentError):
        export_otlp(str(empty))
    out = export_otlp(str(d), str(tmp_path / "o.json"), trace_id=_TID)
    assert json.loads(open(out).read())["resourceSpans"]


def test_otlp_exporter_batches_and_never_raises():
    """The live sink: auto-flush at the batch size, failures counted
    (never raised into the caller), untraced events ignored."""
    from implicitglobalgrid_tpu.telemetry import OtlpSpanExporter

    class Capture(OtlpSpanExporter):
        def __init__(self, **kw):
            super().__init__("http://collector.invalid/v1/traces", **kw)
            self.bodies = []
            self.boom = False

        def _post(self, body):
            if self.boom:
                raise OSError("collector down")
            self.bodies.append(json.loads(body.decode()))

    exp = Capture(batch=2)
    ev = {"kind": "slice", "t": 1.0, "run": "scheduler", "job": "j",
          "trace_id": _TID, "span_id": "1212121212121212"}
    exp.add(dict(ev, seq=0))
    assert not exp.bodies  # below the batch size: buffered
    exp.add({"kind": "slice", "t": 1.0})  # untraced: ignored entirely
    exp(dict(ev, seq=1))  # __call__ alias — usable as a journal sink
    assert len(exp.bodies) == 1 and exp.sent == 2
    spans = _all_spans(exp.bodies[0])
    assert len(spans) == 2 and spans[0]["traceId"] == _TID

    exp.boom = True
    exp.add(dict(ev, seq=2))
    exp.close()  # flushes the short tail; the failure is counted
    assert exp.failed == 1 and "collector down" in exp.last_error
    assert len(exp.bodies) == 1  # nothing new landed

    with pytest.raises(InvalidArgumentError):
        OtlpSpanExporter("")
    with pytest.raises(InvalidArgumentError):
        OtlpSpanExporter("http://x", batch=0)
