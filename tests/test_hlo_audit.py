"""HLO-level audit of the compiled halo exchange.

Guards the framework's core performance claim — "the reference's
pack/send/recv/unpack machinery collapses into one `collective-permute` pair
per exchanging axis" (`ops/halo.py` module docstring) — against XLA
regressions, the way the reference wire-tests its `isend_halo`/`irecv_halo!`
requests (`/root/reference/test/test_update_halo.jl:925-970`): compile the
exchange for a multi-shard mesh and string-match the optimized HLO.
"""

import re

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def _compiled_hlo(dims, periods, shape, n_fields=1, dims_order=None):
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops import halo as halo_mod
    from implicitglobalgrid_tpu.ops.fields import field_partition_spec

    gg = igg.global_grid()
    specs = (field_partition_spec(len(shape)),) * n_fields

    def exchange(*arrays):
        return tuple(halo_mod._exchange_arrays(
            gg, list(arrays),
            [gg.halowidths] * n_fields,
            halo_mod._normalize_dims_order(dims_order),
        ))

    fn = jax.jit(jax.shard_map(
        exchange, mesh=gg.mesh, in_specs=specs, out_specs=specs))
    args = [jnp.zeros(tuple(d * s for d, s in zip(dims, shape)),
                      np.float32) for _ in range(n_fields)]
    return fn.lower(*args).compile().as_text()


def _count_collective_permutes(hlo):
    """collective-permute ops in the optimized HLO (start ops only — the
    async pairs show up as collective-permute-start + -done)."""
    starts = len(re.findall(r"collective-permute-start", hlo))
    if starts:
        return starts
    return len(re.findall(r"= \S* ?collective-permute\(", hlo))


def test_one_permute_pair_per_exchanging_axis():
    """2x2x2 periodic: three exchanging axes -> exactly 6 permutes (one
    left+right pair per axis), none more."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 6


def test_self_neighbor_axes_emit_no_collectives():
    """Periodic single-shard axes take the local-copy path: no collectives
    at all (reference self-neighbor branch, `update_halo.jl:62-68`)."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         dimx=1, dimy=1, dimz=1, quiet=True)
    hlo = _compiled_hlo((1, 1, 1), (1, 1, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 0
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def test_non_exchanging_axis_emits_no_permute():
    """dims=(2,1,4), periody=0: y has no neighbors -> only x and z axes
    exchange -> 4 permutes."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4,
                         periodx=1, periody=0, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 1, 4), (1, 0, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 4


def test_multi_field_shares_no_extra_collectives():
    """Two fields exchanged in one program: permute count scales with
    fields x axes (2 fields x 1 axis x 2 directions = 4), with no hidden
    reduction/gather collectives."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    hlo = _compiled_hlo((8, 1, 1), (1, 0, 0), (8, 8, 8), n_fields=2)
    assert _count_collective_permutes(hlo) == 4
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def test_no_full_array_copies_around_permutes():
    """The permutes must ride on SLAB-sized operands — a full-array-shaped
    copy feeding a collective-permute means XLA failed to fuse the slab
    slicing (the whole point of the design). Checks every permute operand
    shape is a halo slab, not the local block."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (16, 16, 16))
    # operand/result types of collective-permutes: f32[...]{...} shapes
    for m in re.finditer(
            r"collective-permute(?:-start)?\(([^)]*)\)", hlo):
        for shape_m in re.finditer(r"f32\[([0-9,]+)\]", m.group(0)):
            sizes = [int(s) for s in shape_m.group(1).split(",")]
            assert np.prod(sizes) < 16 * 16 * 16, (
                f"full-array-sized collective operand: {sizes}")


def test_permute_count_with_halowidth_2():
    """halowidth>1 exchanges still cost one pair per axis (slab width is
    static, not a per-row loop)."""
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2), quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (12, 12, 12))
    assert _count_collective_permutes(hlo) == 6
