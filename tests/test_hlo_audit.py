"""HLO-level audit of the compiled halo exchange.

Guards the framework's core performance claim — "the reference's
pack/send/recv/unpack machinery collapses into one `collective-permute` pair
per exchanging axis" (`ops/halo.py` module docstring) — against XLA
regressions, the way the reference wire-tests its `isend_halo`/`irecv_halo!`
requests (`/root/reference/test/test_update_halo.jl:925-970`): compile the
exchange for a multi-shard mesh and string-match the optimized HLO.
"""

import re

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.compat import shard_map


def _compiled_hlo(dims, periods, shape, n_fields=1, dims_order=None,
                  coalesce=None, wire=None, dtypes=None, optimized=True):
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.ops import halo as halo_mod
    from implicitglobalgrid_tpu.ops.fields import field_partition_spec
    from implicitglobalgrid_tpu.ops.precision import resolve_wire_dtype

    gg = igg.global_grid()
    specs = (field_partition_spec(len(shape)),) * n_fields
    wire_r = resolve_wire_dtype(wire)

    def exchange(*arrays):
        return tuple(halo_mod._exchange_arrays(
            gg, list(arrays),
            [gg.halowidths] * n_fields,
            halo_mod._normalize_dims_order(dims_order),
            coalesce=coalesce, wire=wire_r,
        ))

    fn = jax.jit(shard_map(
        exchange, mesh=gg.mesh, in_specs=specs, out_specs=specs))
    dtypes = dtypes or [np.float32] * n_fields
    args = [jnp.zeros(tuple(d * s for d, s in zip(dims, shape)), dt)
            for dt in dtypes]
    if optimized:
        return fn.lower(*args).compile().as_text()
    return fn.lower(*args).as_text()


def _count_collective_permutes(hlo):
    """collective-permute ops in the optimized HLO (start ops only — the
    async pairs show up as collective-permute-start + -done)."""
    starts = len(re.findall(r"collective-permute-start", hlo))
    if starts:
        return starts
    return len(re.findall(r"= \S* ?collective-permute\(", hlo))


def test_one_permute_pair_per_exchanging_axis():
    """2x2x2 periodic: three exchanging axes -> exactly 6 permutes (one
    left+right pair per axis), none more."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 6


def test_self_neighbor_axes_emit_no_collectives():
    """Periodic single-shard axes take the local-copy path: no collectives
    at all (reference self-neighbor branch, `update_halo.jl:62-68`)."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         dimx=1, dimy=1, dimz=1, quiet=True)
    hlo = _compiled_hlo((1, 1, 1), (1, 1, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 0
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def test_non_exchanging_axis_emits_no_permute():
    """dims=(2,1,4), periody=0: y has no neighbors -> only x and z axes
    exchange -> 4 permutes."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4,
                         periodx=1, periody=0, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 1, 4), (1, 0, 1), (8, 8, 8))
    assert _count_collective_permutes(hlo) == 4


def test_multi_field_shares_no_extra_collectives():
    """Two same-dtype fields exchanged in one program COALESCE: the axis
    costs one packed permute pair regardless of field count (2, not
    2 fields x 2 directions), with no hidden reduction/gather
    collectives. ``coalesce=False`` restores the per-field 2N scaling."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    hlo = _compiled_hlo((8, 1, 1), (1, 0, 0), (8, 8, 8), n_fields=2)
    assert _count_collective_permutes(hlo) == 2
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    hlo_pf = _compiled_hlo((8, 1, 1), (1, 0, 0), (8, 8, 8), n_fields=2,
                           coalesce=False)
    assert _count_collective_permutes(hlo_pf) == 4
    assert "all-reduce" not in hlo_pf and "all-gather" not in hlo_pf


@pytest.mark.parametrize("n_fields", [2, 4, 8])
def test_coalesced_permute_count_independent_of_field_count(n_fields):
    """THE tentpole claim: on the coalesced path the compiled exchange
    contains exactly 2 ppermutes per exchanged mesh axis for ANY number of
    same-dtype fields (2x2x2 periodic: 3 axes -> 6), where the per-field
    path pays 2 x N x axes."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8), n_fields=n_fields)
    assert _count_collective_permutes(hlo) == 6
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    hlo_pf = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8),
                           n_fields=n_fields, coalesce=False)
    assert _count_collective_permutes(hlo_pf) == 6 * n_fields


def test_coalesced_mixed_dtypes_one_pair_per_group():
    """dtype groups pack separately (the wire payload of one ppermute has
    one dtype): 3 f32 + 2 f64 fields on one exchanging axis -> 2 groups x
    2 directions = 4 permutes, not 2 x 5."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    hlo = _compiled_hlo(
        (8, 1, 1), (1, 0, 0), (8, 8, 8), n_fields=5,
        dtypes=[np.float32] * 3 + [np.float64] * 2)
    assert _count_collective_permutes(hlo) == 4


def test_wire_precision_converts_payload():
    """Wire-precision mode: f32 fields cross the link as bf16 — every
    collective_permute in the LOWERED module (pre-backend-optimization:
    the XLA:CPU float-normalization pass rewrites bf16 payloads back to
    f32 around a convert fusion, TPU keeps them native) carries a bf16
    payload with convert ops around it; OFF by default."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    txt = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8), n_fields=2,
                        wire="bfloat16", optimized=False)
    permute_lines = [ln for ln in txt.splitlines()
                     if "collective_permute" in ln]
    assert len(permute_lines) == 6
    assert all("bf16" in ln for ln in permute_lines), permute_lines
    assert "stablehlo.convert" in txt
    # the optimized program still has one permute pair per axis, and the
    # bf16 rounding survives backend normalization (converts feed the wire)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8), n_fields=2,
                        wire="bfloat16")
    assert _count_collective_permutes(hlo) == 6
    assert "convert" in hlo
    # default: no reduced-precision wire anywhere in the lowered program
    txt_off = _compiled_hlo((2, 2, 2), (1, 1, 1), (8, 8, 8), n_fields=2,
                            optimized=False)
    assert "bf16" not in txt_off


def test_no_full_array_copies_around_permutes():
    """The permutes must ride on SLAB-sized operands — a full-array-shaped
    copy feeding a collective-permute means XLA failed to fuse the slab
    slicing (the whole point of the design). Checks every permute operand
    shape is a halo slab, not the local block."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (16, 16, 16))
    _assert_slab_sized_permutes(hlo, (16, 16, 16))


def _compiled_step_hlo(impl, ndim=3):
    """Optimized HLO of the model step program (the fused Pallas
    step+exchange in interpret mode on the CPU mesh, or the XLA step)."""
    from implicitglobalgrid_tpu.models import (
        init_diffusion2d, init_diffusion3d, make_step,
    )

    if ndim == 3:
        T, Cp, p = init_diffusion3d(dtype=np.float32)
    else:
        T, Cp, p = init_diffusion2d(dtype=np.float32)
    fn = make_step(p, ndim=ndim, impl=impl)
    return fn.lower(T, Cp).compile().as_text()


def _assert_slab_sized_permutes(hlo, local_shape):
    """Every line DEFINING a collective-permute (its result type tuple
    carries the operand/result shapes) must mention only slab-sized f32
    shapes, never the full local block. Lines merely CONSUMING a permute
    result (the `dynamic-update-slice` unpack, buffer tuples) are ignored —
    their output legitimately has the full block shape, and which consumers
    appear as standalone lines varies across XLA versions."""
    block = int(np.prod(local_shape))
    count = 0
    defines = re.compile(r"=[^=]*collective-permute(-start)?\(")
    for line in hlo.splitlines():
        if not defines.search(line):
            continue
        for shape_m in re.finditer(r"f32\[([0-9,]+)\]", line):
            sizes = [int(s) for s in shape_m.group(1).split(",")]
            count += 1
            assert np.prod(sizes) < block, (
                f"full-array-sized collective operand: {sizes}\n{line}")
    assert count > 0  # the scan actually saw permute shapes


def test_fused_step_exchange_one_permute_pair_per_axis():
    """The FUSED Pallas step+exchange (`diffusion3d_step_exchange_pallas`)
    must keep the exchange's wire shape: one slab-sized permute pair per
    exchanging axis (6 on a 2x2x2 periodic mesh), no full-array collective
    operands, no hidden reductions — the perf claim of
    `pallas_stencil.py`'s module comment, audited at the HLO level like the
    reference's wire-level request assertions
    (`test_update_halo.jl:925-970`)."""
    from implicitglobalgrid_tpu.ops.pallas_stencil import step_exchange_modes
    import jax

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct((8, 8, 16), np.float32)) == (True, True, True)
    hlo = _compiled_step_hlo("pallas_interpret")
    assert _count_collective_permutes(hlo) == 6
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    _assert_slab_sized_permutes(hlo, (8, 8, 16))


def test_fused_step_exchange_mixed_mesh_permutes():
    """Mixed self/multi-shard mesh (self x + PROC_NULL y + periodic z):
    only the two ppermute axes emit collectives -> 4 permutes, slab-sized."""
    igg.init_global_grid(8, 8, 16, dimx=1, dimy=2, dimz=4,
                         periodx=1, periody=0, periodz=1, quiet=True)
    hlo = _compiled_step_hlo("pallas_interpret")
    assert _count_collective_permutes(hlo) == 4
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    _assert_slab_sized_permutes(hlo, (8, 8, 16))


def test_fused_step_all_self_emits_no_collectives():
    """All-self mesh: the fused step (multi-plane kernel + in-kernel halo
    fusion) must emit NO collectives at all."""
    igg.init_global_grid(16, 16, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    hlo = _compiled_step_hlo("pallas_interpret")
    assert _count_collective_permutes(hlo) == 0
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def test_fused_step_2d_permutes():
    """2-D fused strip kernel on a 2x2 periodic mesh: 4 slab-sized
    permutes (one pair per axis)."""
    igg.init_global_grid(16, 16, 1, dimx=2, dimy=2, dimz=1,
                         periodx=1, periody=1, quiet=True)
    hlo = _compiled_step_hlo("pallas_interpret", ndim=2)
    assert _count_collective_permutes(hlo) == 4
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    _assert_slab_sized_permutes(hlo, (16, 16))


def test_fused_acoustic_permutes():
    """Fused acoustic pass on a 2x2x2 periodic mesh: 4 fields x 3 axes x 2
    directions = 24 slab-sized permutes, nothing else."""
    from implicitglobalgrid_tpu.models import init_acoustic3d, make_acoustic_run

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_acoustic3d(dtype=np.float32)
    fn = make_acoustic_run(p, 1, impl="pallas_interpret")
    hlo = fn.lower(*state).compile().as_text()
    assert _count_collective_permutes(hlo) == 24
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    _assert_slab_sized_permutes(hlo, (8, 8, 16))


def test_fused_stokes_permutes():
    """Fused Stokes pass on a 2x2x2 periodic mesh: the 4 EXCHANGED fields
    (Pn, Vx, Vy, Vz) x 3 axes x 2 directions = 24 slab-sized permutes —
    the dV fields must not add wire traffic."""
    from implicitglobalgrid_tpu.models import init_stokes3d, make_stokes_run

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    fn = make_stokes_run(p, 1, impl="pallas_interpret")
    hlo = fn.lower(*state).compile().as_text()
    assert _count_collective_permutes(hlo) == 24
    assert "all-reduce" not in hlo and "all-gather" not in hlo
    _assert_slab_sized_permutes(hlo, (8, 8, 16))


def test_fused_acoustic_all_self_no_collectives():
    """The all-self fast path (single shard, periodic everywhere) must
    emit NO collectives: deliveries are in-plane selects / raw source
    slabs inside the kernel (`pallas_common.self_deliver`)."""
    from implicitglobalgrid_tpu.models import init_acoustic3d, make_acoustic_run

    igg.init_global_grid(8, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_acoustic3d(dtype=np.float32)
    fn = make_acoustic_run(p, 1, impl="pallas_interpret")
    hlo = fn.lower(*state).compile().as_text()
    assert _count_collective_permutes(hlo) == 0
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def test_fused_stokes_all_self_no_collectives():
    from implicitglobalgrid_tpu.models import init_stokes3d, make_stokes_run

    igg.init_global_grid(8, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    fn = make_stokes_run(p, 1, impl="pallas_interpret")
    hlo = fn.lower(*state).compile().as_text()
    assert _count_collective_permutes(hlo) == 0
    assert "all-reduce" not in hlo and "all-gather" not in hlo


def _stablehlo_graph(txt):
    """SSA def-use graph of a lowered StableHLO module:
    name -> {op, line, operands}."""
    graph = {}
    for line in txt.splitlines():
        m = re.match(r"\s*(%\d+)(?::\d+)?\s*=\s*(.*)", line)
        if not m:
            continue
        name, rhs = m.groups()
        op = re.search(r"stablehlo\.(\w+)", rhs)
        graph[name] = {
            "op": op.group(1) if op else "",
            "line": line,
            "operands": {f"%{d}" for d in re.findall(r"%(\d+)", rhs)},
        }
    return graph


def _closure(graph, seeds, direction):
    """Transitive producers ('up') or consumers ('down') of ``seeds``."""
    rev = {}
    for name, info in graph.items():
        for opnd in info["operands"]:
            rev.setdefault(opnd, set()).add(name)
    out, stack = set(), list(seeds)
    while stack:
        n = stack.pop()
        nbrs = graph.get(n, {}).get("operands", set()) if direction == "up" \
            else rev.get(n, set())
        for nb in nbrs:
            if nb not in out:
                out.add(nb)
                stack.append(nb)
    return out


def test_overlap_interior_independent_of_permutes():
    """THE structural overlap claim (`ops/overlap.py`): in the lowered
    `hide_communication` step, the interior-update compute must have NO
    SSA path to or from any collective-permute — that independence is
    what lets the latency-hiding scheduler run the interior under the
    collectives on TPU (a single-chip trace can never verify this; the
    round-3 verdict asked for exactly this regression test). Also asserts
    the `optimization_barrier` guarding the stitch is present — without
    it, XLA fuses the (independent) interior INTO the permute-dependent
    stitch fusion and serializes it after the collectives (observed on
    the CPU backend, whose pipeline also strips the barrier before
    fusion, which is why this asserts on the lowered module rather than
    backend-optimized HLO)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.models import init_diffusion3d
    from implicitglobalgrid_tpu.ops.overlap import hide_communication
    from implicitglobalgrid_tpu.ops.stencil import (
        d_xa, d_xi, d_ya, d_yi, d_za, d_zi, inn,
    )

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def up(T, Cp):
        qx = -p.lam * d_xi(T) / p.dx
        qy = -p.lam * d_yi(T) / p.dy
        qz = -p.lam * d_zi(T) / p.dz
        dT = (-d_xa(qx) / p.dx - d_ya(qy) / p.dy - d_za(qz) / p.dz) / inn(Cp)
        return T.at[1:-1, 1:-1, 1:-1].add(p.dt * dT)

    spec = P("gx", "gy", "gz")
    fn = jax.jit(shard_map(
        lambda t, c: hide_communication(up, t, c, radius=1),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    txt = fn.lower(T, Cp).as_text()

    graph = _stablehlo_graph(txt)
    permutes = {n for n, i in graph.items()
                if i["op"] == "collective_permute"}
    assert len(permutes) == 6, permutes  # one pair per exchanging axis
    barriers = {n for n, i in graph.items()
                if i["op"] == "optimization_barrier"}
    assert barriers, (
        "no optimization_barrier around the stitch — TPU fusion is free "
        "to merge the interior compute into the permute-dependent stitch")
    tainted = _closure(graph, permutes, "up") \
        | _closure(graph, permutes, "down") | permutes

    # interior-update compute: arithmetic over the interior-sized block
    # (16^3 local, ol=2 each side -> 12^3), independent of every permute
    interior_ops = {"add", "multiply", "subtract", "divide", "select",
                    "dynamic_update_slice"}
    independent_interior = [
        n for n, i in graph.items()
        if i["op"] in interior_ops
        and "tensor<12x12x12xf32>" in i["line"]
        and n not in tainted
    ]
    assert independent_interior, (
        "no interior-sized compute is independent of the collective-"
        "permutes — the interior was serialized with the exchange "
        "(overlap structurally impossible)")
    # and the barrier consumes the independent interior result (any op
    # kind — the final crop is a `slice`): an interior-sized operand with
    # no path to/from the permutes
    barrier_opnds = set().union(*(graph[b]["operands"] for b in barriers))
    assert any(o in graph and o not in tainted
               and "tensor<12x12x12xf32>" in graph[o]["line"]
               for o in barrier_opnds), (
        "optimization_barrier does not guard the interior result")


def _count_all_reduces(hlo):
    starts = len(re.findall(r"all-reduce-start", hlo))
    if starts:
        return starts
    return len(re.findall(r"= \S* ?all-reduce\(", hlo))


def test_guarded_runner_adds_exactly_one_small_allreduce():
    """THE resilient-runtime wire claim: the health guard fused into a
    chunk (`runtime/health.make_guarded_runner`) costs exactly ONE extra
    collective — a tiny all-reduce of the (2*nfields,) stats vector —
    regardless of field count or chunk length, and does not perturb the
    exchange's permute count (same audit style as the coalescing tests)."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.models.common import make_state_runner
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    for nt_chunk in (1, 4):
        plain = make_state_runner(step, (3, 3), nt_chunk=nt_chunk,
                                  key="hlo_plain")
        guarded = make_guarded_runner(step, (3, 3), nt_chunk=nt_chunk,
                                      key="hlo_guard")
        hlo_p = plain.lower(T, Cp).compile().as_text()
        hlo_g = guarded.lower(T, Cp).compile().as_text()
        assert _count_all_reduces(hlo_p) == 0
        assert _count_all_reduces(hlo_g) == 1
        assert (_count_collective_permutes(hlo_g)
                == _count_collective_permutes(hlo_p))
        # the one collective is TINY: its payload is the (2*nfields,)=4
        # stats vector, never a field-sized buffer
        lines = [ln for ln in hlo_g.splitlines()
                 if re.search(r"= \S* ?all-reduce(-start)?\(", ln)]
        assert lines and all("f32[4]" in ln for ln in lines), lines


def test_telemetry_leaves_chunk_program_untouched(tmp_path):
    """THE observability wire claim (ISSUES 3, 5, and 6): telemetry is
    host-side only — building the guarded chunk runner with an ACTIVE
    flight recorder, live metrics registry, RUNNING metrics server, fresh
    driver heartbeats, AND the performance oracle live (a predict_step
    model attached, a PerfWatch drift detector observing boundaries and
    stamping the igg_perf_* gauges) yields a program with identical
    collective counts and an identical fetch surface (same output arity,
    same parameter count) as with everything off. Zero extra collectives,
    zero extra D2H fetches per chunk (cross-process aggregation and the
    cost model are pure host arithmetic — the heartbeat/server/watch are
    the only RUN-time additions)."""
    import re as _re

    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner
    from implicitglobalgrid_tpu.telemetry import (
        PerfWatch, note_heartbeat, predict_step, start_flight_recorder,
        start_metrics_server, stop_flight_recorder, stop_metrics_server,
    )

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=4, key="hlo_tel_off")
    hlo_off = off.lower(T, Cp).compile().as_text()
    start_flight_recorder(str(tmp_path / "fr.jsonl"))
    start_metrics_server(0)
    try:
        note_heartbeat(0)
        pred = predict_step("diffusion3d", (T, Cp))  # host arithmetic only
        watch = PerfWatch(window=8, model_step_s=pred["step_s"])
        for i in range(6):  # live drift detector + igg_perf_* gauges
            watch.observe(chunk=i, step_begin=4 * i, step_end=4 * i + 4,
                          n=4, exec_s=0.01)
        on = make_guarded_runner(step, (3, 3), nt_chunk=4, key="hlo_tel_on")
        hlo_on = on.lower(T, Cp).compile().as_text()
        out_on = on(T, Cp)
        watch.observe(chunk=6, step_begin=24, step_end=28, n=4,
                      exec_s=0.01)
        note_heartbeat(4)
    finally:
        stop_metrics_server()
        stop_flight_recorder()
    out_off = off(T, Cp)

    assert (_count_collective_permutes(hlo_on)
            == _count_collective_permutes(hlo_off))
    assert _count_all_reduces(hlo_on) == _count_all_reduces(hlo_off) == 1
    assert "all-gather" not in hlo_on and "all-to-all" not in hlo_on
    # identical fetch surface: same program inputs and outputs — the
    # driver's one tiny stats fetch stays the ONLY per-chunk D2H
    for pat in (r"= \S+ parameter\(", r"infeed", r"outfeed"):
        assert (len(_re.findall(pat, hlo_on))
                == len(_re.findall(pat, hlo_off)))
    assert len(out_on) == len(out_off) == 3  # T, Cp, stats vector


def test_reducers_share_the_guard_psum():
    """THE io wire claim (ISSUE 4): an enabled in-situ reducer set adds
    ZERO extra collectives to the chunk program — probe, axis slice and
    global min/max/mean/RMS segments concatenate into the health guard's
    single tiny all-reduce (one psum total, f32[2N + R]), and the
    exchange's permute count is untouched."""
    from implicitglobalgrid_tpu.io.reducers import (
        AxisSlice, Probe, Stats, build_reducer_plan,
        make_reduced_post_chunk,
    )
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.models.common import make_state_runner
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    names = ("T", "Cp")
    reducers = [Probe("T", (0, 0, 0)), AxisSlice("T", 0, (0, 1, 1)),
                Stats("T")]
    plan = build_reducer_plan(reducers, names,
                              {"T": T, "Cp": Cp})
    guarded = make_guarded_runner(step, (3, 3), nt_chunk=2,
                                  key="hlo_io_plain")
    reduced = make_state_runner(
        step, (3, 3), nt_chunk=2, key=("hlo_io_red", plan.signature),
        post_chunk=make_reduced_post_chunk(names, plan))
    hlo_g = guarded.lower(T, Cp).compile().as_text()
    hlo_r = reduced.lower(T, Cp).compile().as_text()
    assert _count_all_reduces(hlo_g) == _count_all_reduces(hlo_r) == 1
    assert (_count_collective_permutes(hlo_r)
            == _count_collective_permutes(hlo_g))
    assert "all-gather" not in hlo_r and "all-to-all" not in hlo_r
    # the ONE collective's payload is the combined stats vector:
    # 2 fields * 2 health entries + probe(1) + slice(12: the implicit
    # global x-size, 2*(8-2) periodic) + stats(2 + 2*8 min/max slots)
    # = 4 + 1 + 12 + 18 = 35 floats
    n = 2 * len(names) + plan.length
    assert plan.length == 1 + 12 + 2 + 2 * 8
    lines = [ln for ln in hlo_r.splitlines()
             if re.search(r"= \S* ?all-reduce(-start)?\(", ln)]
    assert lines and all(f"f32[{n}]" in ln for ln in lines), lines


def test_snapshot_writer_leaves_chunk_program_untouched(tmp_path):
    """Enabling snapshots adds ZERO collectives: with an ACTIVE
    SnapshotWriter (submitting, queue draining) the guarded chunk
    program compiles to identical collective counts and an identical
    fetch surface as with snapshots off — the writer only ever sees the
    host copies `submit` makes at chunk boundaries."""
    import re as _re

    from implicitglobalgrid_tpu.io import SnapshotWriter
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=2, key="hlo_snap_off")
    hlo_off = off.lower(T, Cp).compile().as_text()
    with SnapshotWriter(tmp_path / "s") as w:
        w.submit({"T": T, "Cp": Cp}, 0)
        on = make_guarded_runner(step, (3, 3), nt_chunk=2,
                                 key="hlo_snap_on")
        hlo_on = on.lower(T, Cp).compile().as_text()
        w.flush(timeout=30.0)
    assert (_count_collective_permutes(hlo_on)
            == _count_collective_permutes(hlo_off))
    assert _count_all_reduces(hlo_on) == _count_all_reduces(hlo_off) == 1
    for pat in (r"= \S+ parameter\(", r"infeed", r"outfeed"):
        assert (len(_re.findall(pat, hlo_on))
                == len(_re.findall(pat, hlo_off)))


def test_permute_count_with_halowidth_2():
    """halowidth>1 exchanges still cost one pair per axis (slab width is
    static, not a per-row loop)."""
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2), quiet=True)
    hlo = _compiled_hlo((2, 2, 2), (1, 1, 1), (12, 12, 12))
    assert _count_collective_permutes(hlo) == 6
