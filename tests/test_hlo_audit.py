"""HLO-level audit of the compiled halo exchange — on the `analysis`
subsystem.

Guards the framework's core performance claim — "the reference's
pack/send/recv/unpack machinery collapses into one `collective-permute` pair
per exchanging axis" (`ops/halo.py` module docstring) — against XLA
regressions, the way the reference wire-tests its `isend_halo`/`irecv_halo!`
requests (`/root/reference/test/test_update_halo.jl:925-970`).

Since ISSUE 7 these tests are CONTRACT DECLARATIONS, not regex scans: each
compiles a program, parses it into `analysis.ProgramIR`, and checks it
against a `CollectiveContract` derived from the same static wire plan the
telemetry layer prices (`exchange_contract` = `halo_comm_plan` + topology
routes) — so every assertion is dtype-generic (the old f32-only shape regex
silently skipped bf16/f16/f64 payloads), route-aware (each permute's
``source_target_pairs`` must match a mesh axis of the plan), and
byte-exact (all-links wire bytes, not just op counts). Parser unit tests
against checked-in golden dumps live in tests/test_analysis.py.
"""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.analysis import (
    CollectiveContract, check_contract, exchange_contract, guard_contract,
    parse_program,
)
from implicitglobalgrid_tpu.utils.compat import shard_map

pytestmark = pytest.mark.audit


def _exchange_args(dims, shape, n_fields=1, dtypes=None):
    import jax.numpy as jnp

    dtypes = dtypes or [np.float32] * n_fields
    return [jnp.zeros(tuple(d * s for d, s in zip(dims, shape)), dt)
            for dt in dtypes]


def _compiled_exchange(args, dims_order=None, coalesce=None, wire=None,
                       optimized=True):
    """`ProgramIR` of the compiled multi-field exchange (the program the
    old `_compiled_hlo` regex-scanned)."""
    import jax

    from implicitglobalgrid_tpu.ops import halo as halo_mod
    from implicitglobalgrid_tpu.ops.fields import field_partition_spec
    from implicitglobalgrid_tpu.ops.precision import resolve_wire_dtype

    gg = igg.global_grid()
    n_fields = len(args)
    specs = (field_partition_spec(args[0].ndim),) * n_fields
    wire_r = resolve_wire_dtype(wire)

    def exchange(*arrays):
        return tuple(halo_mod._exchange_arrays(
            gg, list(arrays),
            [gg.halowidths] * n_fields,
            halo_mod._normalize_dims_order(dims_order),
            coalesce=coalesce, wire=wire_r,
        ))

    fn = jax.jit(shard_map(
        exchange, mesh=gg.mesh, in_specs=specs, out_specs=specs))
    return parse_program(fn, *args, optimized=optimized)


def _assert_honors(ir, contract):
    findings = check_contract(ir, contract)
    assert not findings, [f.to_json() for f in findings]


def test_one_permute_pair_per_exchanging_axis():
    """2x2x2 periodic: three exchanging axes -> exactly 6 permutes (one
    left+right pair per axis), each on a legal route of its axis, each
    slab-sized, with the plan's exact all-links wire bytes — none more."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    args = _exchange_args((2, 2, 2), (8, 8, 8))
    ir = _compiled_exchange(args)
    contract = exchange_contract(*args)
    assert sorted(contract.axes) == ["gx", "gy", "gz"]
    assert all(v["permutes"] == 2 for v in contract.axes.values())
    _assert_honors(ir, contract)
    assert len(ir.permutes) == 6


def test_self_neighbor_axes_emit_no_collectives():
    """Periodic single-shard axes take the local-copy path: no collectives
    at all (reference self-neighbor branch, `update_halo.jl:62-68`)."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         dimx=1, dimy=1, dimz=1, quiet=True)
    args = _exchange_args((1, 1, 1), (8, 8, 8))
    ir = _compiled_exchange(args)
    contract = exchange_contract(*args)
    assert contract.axes == {}  # the plan prices zero wire traffic
    _assert_honors(ir, contract)
    assert not ir.permutes and not ir.all_reduces and not ir.all_gathers


def test_non_exchanging_axis_emits_no_permute():
    """dims=(2,1,4), periody=0: y has no neighbors -> only x and z axes
    exchange -> 4 permutes, and every permute rides an x- or z-route."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4,
                         periodx=1, periody=0, periodz=1, quiet=True)
    args = _exchange_args((2, 1, 4), (8, 8, 8))
    ir = _compiled_exchange(args)
    contract = exchange_contract(*args)
    assert sorted(contract.axes) == ["gx", "gz"]
    _assert_honors(ir, contract)
    assert len(ir.permutes) == 4


def test_multi_field_shares_no_extra_collectives():
    """Two same-dtype fields exchanged in one program COALESCE: the axis
    costs one packed permute pair regardless of field count (2, not
    2 fields x 2 directions), with no hidden reduction/gather
    collectives. ``coalesce=False`` restores the per-field 2N scaling."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    args = _exchange_args((8, 1, 1), (8, 8, 8), n_fields=2)
    _assert_honors(_compiled_exchange(args), exchange_contract(*args))
    assert exchange_contract(*args).axes["gx"]["permutes"] == 2
    pf = exchange_contract(*args, coalesce=False)
    assert pf.axes["gx"]["permutes"] == 4
    _assert_honors(_compiled_exchange(args, coalesce=False), pf)


@pytest.mark.parametrize("n_fields", [2, 4, 8])
def test_coalesced_permute_count_independent_of_field_count(n_fields):
    """THE tentpole claim: on the coalesced path the compiled exchange
    contains exactly 2 ppermutes per exchanged mesh axis for ANY number of
    same-dtype fields (2x2x2 periodic: 3 axes -> 6), where the per-field
    path pays 2 x N x axes."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    args = _exchange_args((2, 2, 2), (8, 8, 8), n_fields=n_fields)
    contract = exchange_contract(*args)
    assert all(v["permutes"] == 2 for v in contract.axes.values())
    _assert_honors(_compiled_exchange(args), contract)
    pf = exchange_contract(*args, coalesce=False)
    assert all(v["permutes"] == 2 * n_fields for v in pf.axes.values())
    _assert_honors(_compiled_exchange(args, coalesce=False), pf)


def test_coalesced_mixed_dtypes_one_pair_per_group():
    """dtype groups pack separately (the wire payload of one ppermute has
    one dtype): 3 f32 + 2 f64 fields on one exchanging axis -> 2 groups x
    2 directions = 4 permutes, not 2 x 5 — and the f64 payloads are
    route/slab/byte-audited exactly like the f32 ones (the old f32-only
    regex was blind to them)."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    args = _exchange_args((8, 1, 1), (8, 8, 8), n_fields=5,
                          dtypes=[np.float32] * 3 + [np.float64] * 2)
    contract = exchange_contract(*args)
    assert contract.axes["gx"]["permutes"] == 4
    assert sorted(contract.axes["gx"]["dtypes"]) == ["f32", "f64"]
    ir = _compiled_exchange(args)
    _assert_honors(ir, contract)
    payloads = {str(ir.payload_of(p)) for p in ir.permutes}
    assert any(s.startswith("f64") for s in payloads), payloads


def test_wire_precision_converts_payload():
    """Wire-precision mode: f32 fields cross the link as bf16 — every
    collective_permute in the LOWERED module (pre-backend-optimization:
    the XLA:CPU float-normalization pass rewrites bf16 payloads back to
    f32 around a convert fusion, TPU keeps them native) carries a
    bf16 SLAB-SIZED payload on a legal route with the plan's (halved)
    wire bytes; OFF by default."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    args = _exchange_args((2, 2, 2), (8, 8, 8), n_fields=2)
    contract = exchange_contract(*args, wire_dtype="bfloat16")
    assert all(v["dtypes"] == ("bf16",) for v in contract.axes.values())
    ir = _compiled_exchange(args, wire="bfloat16", optimized=False)
    _assert_honors(ir, contract)
    assert len(ir.permutes) == 6
    assert all(ir.payload_of(p).dtype == "bf16" for p in ir.permutes)
    assert ir.count("convert") > 0
    # the wire-downcast lint agrees the narrowing reached the wire
    from implicitglobalgrid_tpu.analysis import default_lint_config, run_lints
    cfg = default_lint_config(state_dtypes=("f32",), wire_dtype="bfloat16")
    assert run_lints(ir, config=cfg, rules=("wire-downcast-missing",)) == []
    # the optimized program still has one permute pair per axis, and the
    # bf16 rounding survives backend normalization (converts feed the wire)
    ir_opt = _compiled_exchange(args, wire="bfloat16")
    assert len(ir_opt.permutes) == 6
    assert ir_opt.count("convert") > 0
    # default: no reduced-precision wire anywhere in the lowered program,
    # and the lint CATCHES a requested-but-absent downcast
    ir_off = _compiled_exchange(args, optimized=False)
    assert not any(op.has_shape("bf16") for op in ir_off.ops)
    missing = run_lints(ir_off, config=cfg,
                        rules=("wire-downcast-missing",))
    assert [f.rule for f in missing] == ["wire-downcast-missing"]
    assert missing[0].severity == "error"


@pytest.mark.quant
def test_quantized_wire_contract_one_pair_s8_bytes_exact():
    """THE quantized-wire claim (ISSUE 10): with ``wire_dtype="int8"``
    the 4-field coalesced exchange still compiles to ONE ppermute pair
    per exchanging axis (collective count unchanged), every payload is
    the packed s8 buffer (slabs + bitcast per-slab f32 scales), the
    plan's wire bytes match the compiled program TO THE BYTE — and sit
    >= 3.5x below the f32 plan at 4 fields. int8 payloads survive
    backend optimization (no float-normalization), so this is the DEEP
    post-SPMD audit, not the lowered-module fallback bf16 needs."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4,
                         periodx=1, periodz=1, quiet=True)
    args = _exchange_args((2, 1, 4), (8, 8, 8), n_fields=4)
    contract = exchange_contract(*args, wire_dtype="int8")
    assert sorted(contract.axes) == ["gx", "gz"]
    assert all(v["permutes"] == 2 and v["dtypes"] == ("s8",)
               for v in contract.axes.values())
    ir = _compiled_exchange(args, wire="int8")  # optimized HLO
    _assert_honors(ir, contract)
    assert len(ir.permutes) == 4
    assert all(ir.payload_of(p).dtype == "s8" for p in ir.permutes)
    # byte accounting: >= 3.5x below f32 at 4 fields (the EQuARX-style
    # 3.75x target region; scales cost 4B per slab against 4x on cells)
    exact = exchange_contract(*args)
    for axis in ("gx", "gz"):
        ratio = (exact.axes[axis]["wire_bytes"]
                 / contract.axes[axis]["wire_bytes"])
        assert ratio >= 3.5, (axis, ratio)
    # int4: same pair count, halved payload again (>= 7x total)
    c4 = exchange_contract(*args, wire_dtype="int4")
    _assert_honors(_compiled_exchange(args, wire="int4"), c4)
    for axis in ("gx", "gz"):
        assert (exact.axes[axis]["wire_bytes"]
                / c4.axes[axis]["wire_bytes"]) >= 7.0


@pytest.mark.quant
def test_quantized_wire_per_axis_policy_contract():
    """Per-axis policy proven at the HLO level: one compiled 2-axis
    program under ``wire_dtype="z:int8,x:f32"`` carries EXACT f32
    payloads on the x axis and packed s8 payloads on the z axis, honors
    the plan's per-axis bytes, and the per-axis-aware wire-downcast lint
    agrees (full-width x payloads are legal under the mixed policy — the
    pre-policy global check would have flagged them)."""
    from implicitglobalgrid_tpu.analysis import (
        default_lint_config, measure_axes, run_lints,
    )
    from implicitglobalgrid_tpu.analysis.contracts import axis_routes

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4,
                         periodx=1, periodz=1, quiet=True)
    args = _exchange_args((2, 1, 4), (8, 8, 8), n_fields=2)
    contract = exchange_contract(*args, wire_dtype="z:int8,x:f32")
    assert contract.axes["gx"]["dtypes"] == ("f32",)
    assert contract.axes["gz"]["dtypes"] == ("s8",)
    ir = _compiled_exchange(args, wire="z:int8,x:f32")
    _assert_honors(ir, contract)
    by_axis = measure_axes(ir, axis_routes())
    assert by_axis["gx"]["dtypes"] == ("f32",)
    assert by_axis["gz"]["dtypes"] == ("s8",)
    # exact x bytes == the full-precision plan's; quantized z bytes <<
    exact = exchange_contract(*args)
    assert (contract.axes["gx"]["wire_bytes"]
            == exact.axes["gx"]["wire_bytes"])
    assert (contract.axes["gz"]["wire_bytes"] * 3.5
            <= exact.axes["gz"]["wire_bytes"])
    # lint: mixed program clean under the mixed policy; an all-exact
    # program still flags (z narrowing missing); and the quantized
    # program is clean under a UNIFORM int8 policy too (s8 payloads are
    # never stale — integer widths are legal under any wider policy)
    cfg = default_lint_config(state_dtypes=("f32",),
                              wire_dtype="z:int8,x:f32")
    assert run_lints(ir, config=cfg,
                     rules=("wire-downcast-missing",)) == []
    ir_off = _compiled_exchange(args)
    stale = run_lints(ir_off, config=cfg,
                      rules=("wire-downcast-missing",))
    assert [f.rule for f in stale] == ["wire-downcast-missing"]


def test_no_full_array_copies_around_permutes():
    """The permutes must ride on SLAB-sized operands — a full-array-shaped
    payload feeding a collective-permute means XLA failed to fuse the slab
    slicing (the whole point of the design). `exchange_contract` bounds
    every permute payload strictly below the local block."""
    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    args = _exchange_args((2, 2, 2), (16, 16, 16))
    contract = exchange_contract(*args)
    assert contract.max_payload_cells == 16 ** 3
    ir = _compiled_exchange(args)
    _assert_honors(ir, contract)
    assert len(ir.permutes) == 6  # the slab audit actually saw permutes


def test_slab_audit_is_dtype_generic():
    """REGRESSION (ISSUE 7 satellite): the old `_assert_slab_sized_permutes`
    only recognized ``f32[...]`` shapes, so bf16 wire payloads and f64
    fields were invisible to the slab check. The contract bound is
    dtype-blind: a block-sized bf16 or f64 permute payload must FAIL."""
    block = "bf16[8,8,8]", "f64[8,8,8]", "f32[8,8,8]"
    for shape in block:
        text = f"""HloModule synthetic_{shape.split('[')[0]}

ENTRY %main (p0: {shape}) -> {shape} {{
  %p0 = {shape} parameter(0)
  ROOT %cp = {shape} collective-permute(%p0), source_target_pairs={{{{0,1}},{{1,0}}}}
}}
"""
        ir = parse_program(text)
        findings = check_contract(
            ir, CollectiveContract(max_payload_cells=8 ** 3))
        assert [f.rule for f in findings] == ["permute-payload"], shape
        # ... while a genuinely slab-sized payload of the same dtype passes
        slab = shape.replace("[8,8,8]", "[1,8,8]")
        ok_text = text.replace(shape, slab)
        assert check_contract(parse_program(ok_text),
                              CollectiveContract(max_payload_cells=8 ** 3)) \
            == []


def test_live_bf16_and_f64_payloads_are_slab_audited():
    """The live counterpart: a bf16-wire exchange (lowered module) and an
    f64-field exchange (optimized module) both carry non-f32 payloads, and
    the contract's slab bound demonstrably COVERS them — tighten the bound
    below the actual slab size and the same programs fail."""
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, quiet=True)
    cases = [
        (_exchange_args((8, 1, 1), (8, 8, 8), dtypes=[np.float64]),
         dict(optimized=True), "f64"),
        (_exchange_args((8, 1, 1), (8, 8, 8)),
         dict(wire="bfloat16", optimized=False), "bf16"),
    ]
    for args, build, dtype in cases:
        ir = _compiled_exchange(args, **build)
        assert {ir.payload_of(p).dtype for p in ir.permutes} == {dtype}
        _assert_honors(ir, exchange_contract(
            *args, wire_dtype="bfloat16" if dtype == "bf16" else None))
        too_tight = CollectiveContract(max_payload_cells=1)
        bad = check_contract(ir, too_tight)
        assert {f.rule for f in bad} == {"permute-payload"}, dtype
        assert len(bad) == len(ir.permutes)


def _compiled_step_ir(impl, ndim=3):
    """`ProgramIR` of the optimized model step program (the fused Pallas
    step+exchange in interpret mode on the CPU mesh, or the XLA step)."""
    from implicitglobalgrid_tpu.models import (
        init_diffusion2d, init_diffusion3d, make_step,
    )

    if ndim == 3:
        T, Cp, p = init_diffusion3d(dtype=np.float32)
    else:
        T, Cp, p = init_diffusion2d(dtype=np.float32)
    fn = make_step(p, ndim=ndim, impl=impl)
    return parse_program(fn, T, Cp)


def _fused_contract(local_shape, n_permutes):
    """Structural pin of a fused program's permute count: slab bound,
    forbidden reductions/gathers, and route legality from the subsystem
    (the full byte-exact plan contracts are checked by the audit_model
    tests below — since the fused tier rides the canonical wire schema,
    those are REAL `model_contract`s, not a per-field carve-out)."""
    from implicitglobalgrid_tpu.analysis import axis_routes

    return CollectiveContract(
        routes=axis_routes(), allreduces=0,
        max_payload_cells=int(np.prod(local_shape)),
        meta={"n_permutes": n_permutes})


def _assert_fused(ir, local_shape, n_permutes):
    _assert_honors(ir, _fused_contract(local_shape, n_permutes))
    assert len(ir.permutes) == n_permutes
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls


def test_fused_step_exchange_one_permute_pair_per_axis():
    """The FUSED Pallas step+exchange (`diffusion3d_step_exchange_pallas`)
    must keep the exchange's wire shape: one slab-sized permute pair per
    exchanging axis (6 on a 2x2x2 periodic mesh) riding legal axis routes,
    no full-array collective operands, no hidden reductions — the perf
    claim of `pallas_stencil.py`'s module comment, audited at the HLO
    level like the reference's wire-level request assertions
    (`test_update_halo.jl:925-970`)."""
    import jax

    from implicitglobalgrid_tpu.ops.pallas_stencil import step_exchange_modes

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct((8, 8, 16), np.float32)) \
        == (True, True, True)
    _assert_fused(_compiled_step_ir("pallas_interpret"), (8, 8, 16), 6)


def test_fused_step_exchange_mixed_mesh_permutes():
    """Mixed self/multi-shard mesh (self x + PROC_NULL y + periodic z):
    only the two ppermute axes emit collectives -> 4 permutes, slab-sized."""
    igg.init_global_grid(8, 8, 16, dimx=1, dimy=2, dimz=4,
                         periodx=1, periody=0, periodz=1, quiet=True)
    _assert_fused(_compiled_step_ir("pallas_interpret"), (8, 8, 16), 4)


def test_fused_step_all_self_emits_no_collectives():
    """All-self mesh: the fused step (multi-plane kernel + in-kernel halo
    fusion) must emit NO collectives at all."""
    igg.init_global_grid(16, 16, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    _assert_fused(_compiled_step_ir("pallas_interpret"), (16, 16, 16), 0)


def test_fused_step_2d_permutes():
    """2-D fused strip kernel on a 2x2 periodic mesh: 4 slab-sized
    permutes (one pair per axis)."""
    igg.init_global_grid(16, 16, 1, dimx=2, dimy=2, dimz=1,
                         periodx=1, periody=1, quiet=True)
    _assert_fused(_compiled_step_ir("pallas_interpret", ndim=2),
                  (16, 16), 4)


def test_fused_acoustic_permutes():
    """Fused acoustic pass on a 2x2x2 periodic mesh: all 4 fields ride the
    canonical PACKED wire (one ppermute pair per mesh axis for the whole
    round — `exchange_recv_slabs_multi`) = 6 slab-sized permutes, down
    from the pre-schema per-field 24, byte-exact to the fused-round
    contract."""
    from implicitglobalgrid_tpu.analysis import model_contract
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, make_acoustic_run,
    )

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_acoustic3d(dtype=np.float32)
    fn = make_acoustic_run(p, 1, impl="pallas_interpret")
    ir = parse_program(fn, *state)
    _assert_fused(ir, (8, 8, 16), 6)
    contract = model_contract("acoustic3d", state, impl="pallas")
    assert all(v["permutes"] == 2 for v in contract.axes.values())
    _assert_honors(ir, contract)


def test_fused_stokes_permutes():
    """Fused Stokes pass on a 2x2x2 periodic mesh: the 4 EXCHANGED fields
    (Pn, Vx, Vy, Vz) pack into one ppermute pair per mesh axis = 6
    slab-sized permutes (pre-schema: 24 per-field) — the dV fields must
    not add wire traffic, and the payload is byte-exact to the plan."""
    from implicitglobalgrid_tpu.analysis import model_contract
    from implicitglobalgrid_tpu.models import init_stokes3d, make_stokes_run

    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    fn = make_stokes_run(p, 1, impl="pallas_interpret")
    ir = parse_program(fn, *state)
    _assert_fused(ir, (8, 8, 16), 6)
    _assert_honors(ir, model_contract("stokes3d", state, impl="pallas"))


@pytest.mark.slow
def test_fused_acoustic_all_self_no_collectives():
    """The all-self fast path (single shard, periodic everywhere) must
    emit NO collectives: deliveries are in-plane selects / raw source
    slabs inside the kernel (`pallas_common.self_deliver`).

    `slow`: the all-self-mesh claim keeps
    `test_fused_step_all_self_emits_no_collectives` (diffusion) as its
    fast tier-1 representative; these per-family variants ride the slow
    tier (tier-1 wall-time budget, see ROADMAP)."""
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, make_acoustic_run,
    )

    igg.init_global_grid(8, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_acoustic3d(dtype=np.float32)
    fn = make_acoustic_run(p, 1, impl="pallas_interpret")
    _assert_fused(parse_program(fn, *state), (8, 8, 16), 0)


@pytest.mark.slow
def test_fused_stokes_all_self_no_collectives():
    from implicitglobalgrid_tpu.models import init_stokes3d, make_stokes_run

    igg.init_global_grid(8, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    fn = make_stokes_run(p, 1, impl="pallas_interpret")
    _assert_fused(parse_program(fn, *state), (8, 8, 16), 0)


def test_overlap_interior_independent_of_permutes():
    """THE structural overlap claim (`ops/overlap.py`): in the lowered
    `hide_communication` step, the interior-update compute must have NO
    SSA path to or from any collective-permute — that independence is
    what lets the latency-hiding scheduler run the interior under the
    collectives on TPU (a single-chip trace can never verify this; the
    round-3 verdict asked for exactly this regression test). Also asserts
    the `optimization_barrier` guarding the stitch is present — without
    it, XLA fuses the (independent) interior INTO the permute-dependent
    stitch fusion and serializes it after the collectives (observed on
    the CPU backend, whose pipeline also strips the barrier before
    fusion, which is why this asserts on the lowered module rather than
    backend-optimized HLO). Runs on `ProgramIR.closure`, the def-use
    graph the parser builds for either dialect."""
    import jax
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.models import init_diffusion3d
    from implicitglobalgrid_tpu.ops.overlap import hide_communication
    from implicitglobalgrid_tpu.ops.stencil import (
        d_xa, d_xi, d_ya, d_yi, d_za, d_zi, inn,
    )

    igg.init_global_grid(16, 16, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def up(T, Cp):
        qx = -p.lam * d_xi(T) / p.dx
        qy = -p.lam * d_yi(T) / p.dy
        qz = -p.lam * d_zi(T) / p.dz
        dT = (-d_xa(qx) / p.dx - d_ya(qy) / p.dy - d_za(qz) / p.dz) / inn(Cp)
        return T.at[1:-1, 1:-1, 1:-1].add(p.dt * dT)

    spec = P("gx", "gy", "gz")
    fn = jax.jit(shard_map(
        lambda t, c: hide_communication(up, t, c, radius=1),
        mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec))
    ir = parse_program(fn, T, Cp, optimized=False)

    permutes = ir.permutes
    assert len(permutes) == 6  # one pair per exchanging axis
    barriers = ir.find("optimization-barrier")
    assert barriers, (
        "no optimization_barrier around the stitch — TPU fusion is free "
        "to merge the interior compute into the permute-dependent stitch")
    tainted = ir.closure(permutes, "up") | ir.closure(permutes, "down") \
        | set(permutes)

    # interior-update compute: arithmetic over the interior-sized block
    # (16^3 local, ol=2 each side -> 12^3), independent of every permute
    def interior_sized(op):
        return any(s.dtype == "f32" and s.dims == (12, 12, 12)
                   for s in op.shapes)

    interior_ops = {"add", "multiply", "subtract", "divide", "select",
                    "dynamic-update-slice"}
    independent_interior = [
        op for op in ir.ops
        if op.op in interior_ops and interior_sized(op)
        and op not in tainted]
    assert independent_interior, (
        "no interior-sized compute is independent of the collective-"
        "permutes — the interior was serialized with the exchange "
        "(overlap structurally impossible)")
    # and the barrier consumes the independent interior result (any op
    # kind — the final crop is a `slice`): an interior-sized operand with
    # no path to/from the permutes
    barrier_feeds = [
        prod for b in barriers for name in b.operands
        if (prod := ir.resolve(b.computation, name)) is not None]
    assert any(interior_sized(prod) and prod not in tainted
               for prod in barrier_feeds), (
        "optimization_barrier does not guard the interior result")


def _assert_interior_first(ir, min_cells, n_permutes):
    """Structural interior-first claim on a LOWERED step program: the
    expected permute count, an optimization_barrier guarding the stitch,
    and interior-scale f32 compute with NO SSA path to or from any
    collective-permute (`ProgramIR.closure`)."""
    permutes = ir.permutes
    assert len(permutes) == n_permutes
    assert ir.find("optimization-barrier"), (
        "no optimization_barrier around the stitch — fusion is free to "
        "serialize the interior after the collectives")
    tainted = ir.closure(permutes, "up") | ir.closure(permutes, "down") \
        | set(permutes)
    interior_ops = {"add", "multiply", "subtract", "divide", "select",
                    "dynamic-update-slice"}

    def big(op):
        return any(s.dtype == "f32" and s.dims
                   and int(np.prod(s.dims)) >= min_cells
                   for s in op.shapes)

    independent = [op for op in ir.ops
                   if op.op in interior_ops and big(op)
                   and op not in tainted]
    assert independent, (
        "no interior-scale compute is independent of the collective-"
        "permutes — the interior-first shape degraded to a serialized "
        "exchange")


def test_overlap_interior_first_acoustic_multi_field():
    """The MULTI-FIELD interior-first round (the acoustic V round: three
    STAGGERED outputs, ONE coalesced ppermute pair per axis) keeps its
    collectives structurally independent of its interior update — the
    live `ProgramIR.closure` check of the ISSUE-11 acceptance. Audited on
    the round in isolation: in the full two-round step, round 2's shell
    legitimately consumes round 1's exchanged halos, so the per-round
    independence is the invariant (diffusion's single-field form is
    audited above; the stokes 7-field single-round form rides the slow
    tier; the golden host-only counterpart is
    tests/data/hlo/overlap_interior_first.stablehlo.txt)."""
    from jax import lax

    from implicitglobalgrid_tpu.models import init_acoustic3d
    from implicitglobalgrid_tpu.models.common import interior_first_step

    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    (Pf, Vx, Vy, Vz), p = init_acoustic3d(dtype=np.float32, overlap=True)

    def dP(A, d):
        n = A.shape[d]
        return (lax.slice_in_dim(A, 1, n, axis=d)
                - lax.slice_in_dim(A, 0, n - 1, axis=d))

    def v_upd(vx, vy, vz, Pc):
        vx = vx.at[1:-1, :, :].add(-p.dt / p.rho * dP(Pc, 0) / p.dx)
        vy = vy.at[:, 1:-1, :].add(-p.dt / p.rho * dP(Pc, 1) / p.dy)
        vz = vz.at[:, :, 1:-1].add(-p.dt / p.rho * dP(Pc, 2) / p.dz)
        return vx, vy, vz

    from jax.sharding import PartitionSpec as P
    import jax

    spec = P("gx", "gy", "gz")
    fn = jax.jit(shard_map(
        lambda vx, vy, vz, Pc: interior_first_step(
            v_upd, (vx, vy, vz), (Pc,), radius=1),
        mesh=gg.mesh, in_specs=(spec,) * 4, out_specs=(spec,) * 3))
    ir = parse_program(fn, Vx, Vy, Vz, Pf, optimized=False)
    # one coalesced 3-field pair per exchanging axis
    _assert_interior_first(ir, min_cells=(12 - 4) ** 3, n_permutes=6)


@pytest.mark.slow
def test_overlap_interior_first_stokes_multi_field():
    """The 7-output / 4-exchanged stokes interior-first iteration: one
    coalesced (Vx, Vy, Vz, Pn) ppermute round per axis, interior PT
    update independent of every permute."""
    from implicitglobalgrid_tpu.models import (
        init_stokes3d, stokes_step_local,
    )

    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    state, p = init_stokes3d(dtype=np.float32, overlap=True)
    from jax.sharding import PartitionSpec as P
    import jax

    spec = P("gx", "gy", "gz")
    fn = jax.jit(shard_map(
        lambda *s: stokes_step_local(s, p, impl="xla"),
        mesh=gg.mesh, in_specs=(spec,) * 8, out_specs=(spec,) * 8))
    ir = parse_program(fn, *state, optimized=False)
    _assert_interior_first(ir, min_cells=(12 - 4) ** 3, n_permutes=6)


def test_guarded_runner_adds_exactly_one_small_allreduce():
    """THE resilient-runtime wire claim: the health guard fused into a
    chunk (`runtime/health.make_guarded_runner`) costs exactly ONE extra
    collective — a tiny all-reduce of the (2*nfields,) stats vector —
    regardless of field count or chunk length, and does not perturb the
    exchange's permute count (`guard_contract`, the same contract
    `run_resilient(audit=True)` checks at compile time)."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.models.common import make_state_runner
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    for nt_chunk in (1, 4):
        plain = make_state_runner(step, (3, 3), nt_chunk=nt_chunk,
                                  key="hlo_plain")
        guarded = make_guarded_runner(step, (3, 3), nt_chunk=nt_chunk,
                                      key="hlo_guard")
        ir_p = parse_program(plain, T, Cp)
        ir_g = parse_program(guarded, T, Cp)
        # the plain chunk: zero reductions, zero gathers
        _assert_honors(ir_p, CollectiveContract(allreduces=0))
        # the guarded chunk: exactly one f32[4] psum, gathers forbidden,
        # payload checked on EVERY all-reduce present
        _assert_honors(ir_g, guard_contract(2))
        assert len(ir_g.all_reduces) == 1
        assert (len(ir_g.permutes) == len(ir_p.permutes))


def test_run_resilient_audit_leaves_chunk_program_untouched(tmp_path):
    """THE ISSUE-7 wire claim: `run_resilient(audit=True)` audits the
    chunk program at COMPILE time only — trace+lower, no second backend
    compile — so the XLA executable the run dispatches is built exactly
    as without the audit: identical collective counts, identical fetch
    surface (same parameter count, no infeed/outfeed), and the run's
    results are bit-identical. The audit's verdict streams to the flight
    recorder (one ``audit`` event -> `run_report`'s ``"audit"`` section)
    and the ``igg_audit_findings_total`` family."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner
    from implicitglobalgrid_tpu.telemetry import (
        read_flight_events, run_report, start_flight_recorder,
        stop_flight_recorder,
    )

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    # reference program, no audit anywhere near it
    def tup_step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    ref = make_guarded_runner(tup_step, (3, 3), nt_chunk=2, key="aud_ref")
    ir_ref = parse_program(ref, T, Cp)

    jsonl = tmp_path / "fr.jsonl"
    start_flight_recorder(str(jsonl))
    try:
        st_a, _ = igg.run_resilient(step, {"T": T, "Cp": Cp}, 4,
                                    nt_chunk=2, audit=True)
    finally:
        stop_flight_recorder()
    st_p, _ = igg.run_resilient(step, {"T": T, "Cp": Cp}, 4, nt_chunk=2)
    assert np.array_equal(np.asarray(st_a["T"]), np.asarray(st_p["T"]))

    # the audited run's chunk program == the reference guarded program
    run = make_guarded_runner(tup_step, (3, 3), nt_chunk=2, key="aud_run")
    ir_run = parse_program(run, T, Cp)
    assert len(ir_run.permutes) == len(ir_ref.permutes)
    assert len(ir_run.all_reduces) == len(ir_ref.all_reduces) == 1
    assert not ir_run.all_gathers and not ir_run.all_to_alls
    assert len(ir_run.parameters()) == len(ir_ref.parameters())
    assert ir_run.count("infeed") == ir_run.count("outfeed") == 0

    # verdict reached the flight recorder and the report's audit section
    evs = read_flight_events(str(jsonl))
    audits = [e for e in evs if e.get("kind") == "audit"]
    assert len(audits) == 1 and audits[0]["ok"] \
        and audits[0]["dialect"] == "stablehlo"
    section = run_report(str(jsonl), include_metrics=False)["audit"]
    assert section["programs"] == 1 and section["ok"] is True
    assert section["errors"] == 0 and section["findings"] == []


def test_telemetry_leaves_chunk_program_untouched(tmp_path):
    """THE observability wire claim (ISSUES 3, 5, and 6): telemetry is
    host-side only — building the guarded chunk runner with an ACTIVE
    flight recorder, live metrics registry, RUNNING metrics server, fresh
    driver heartbeats, AND the performance oracle live (a predict_step
    model attached, a PerfWatch drift detector observing boundaries and
    stamping the igg_perf_* gauges) yields a program with identical
    collective counts and an identical fetch surface (same output arity,
    same parameter count) as with everything off. Zero extra collectives,
    zero extra D2H fetches per chunk (cross-process aggregation and the
    cost model are pure host arithmetic — the heartbeat/server/watch are
    the only RUN-time additions)."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner
    from implicitglobalgrid_tpu.telemetry import (
        PerfWatch, note_heartbeat, predict_step, start_flight_recorder,
        start_metrics_server, stop_flight_recorder, stop_metrics_server,
    )

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=4, key="hlo_tel_off")
    ir_off = parse_program(off, T, Cp)
    start_flight_recorder(str(tmp_path / "fr.jsonl"))
    start_metrics_server(0)
    try:
        note_heartbeat(0)
        pred = predict_step("diffusion3d", (T, Cp))  # host arithmetic only
        watch = PerfWatch(window=8, model_step_s=pred["step_s"])
        for i in range(6):  # live drift detector + igg_perf_* gauges
            watch.observe(chunk=i, step_begin=4 * i, step_end=4 * i + 4,
                          n=4, exec_s=0.01)
        on = make_guarded_runner(step, (3, 3), nt_chunk=4, key="hlo_tel_on")
        ir_on = parse_program(on, T, Cp)
        out_on = on(T, Cp)
        watch.observe(chunk=6, step_begin=24, step_end=28, n=4,
                      exec_s=0.01)
        note_heartbeat(4)
    finally:
        stop_metrics_server()
        stop_flight_recorder()
    out_off = off(T, Cp)

    assert len(ir_on.permutes) == len(ir_off.permutes)
    assert len(ir_on.all_reduces) == len(ir_off.all_reduces) == 1
    assert not ir_on.all_gathers and not ir_on.all_to_alls
    # identical fetch surface: same program inputs and outputs — the
    # driver's one tiny stats fetch stays the ONLY per-chunk D2H
    assert len(ir_on.parameters()) == len(ir_off.parameters())
    for op in ("infeed", "outfeed"):
        assert ir_on.count(op) == ir_off.count(op) == 0
    assert len(out_on) == len(out_off) == 3  # T, Cp, stats vector


def test_tracing_leaves_chunk_program_untouched(tmp_path):
    """THE ISSUE-20 wire claim: distributed tracing is host-side dict
    stamping only — building and running the guarded chunk runner while
    the active flight recorder carries a `TraceContext` (every record
    stamped with the trace id and the job-root parent span) yields a
    program with identical collective counts and an identical fetch
    surface as untraced, and bit-identical outputs. The trace rides the
    JSONL records, never the compiled program."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner
    from implicitglobalgrid_tpu.telemetry import (
        TraceContext, flight_recorder, read_flight_events,
        start_flight_recorder, stop_flight_recorder,
    )

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=4, key="hlo_tr_off")
    ir_off = parse_program(off, T, Cp)
    out_off = off(T, Cp)

    tr = TraceContext.new().child()  # the job root, as the scheduler sets
    start_flight_recorder(str(tmp_path / "fr.jsonl"))
    try:
        flight_recorder().trace = tr
        igg.record_event("run_begin", nt=4)
        on = make_guarded_runner(step, (3, 3), nt_chunk=4,
                                 key="hlo_tr_on")
        ir_on = parse_program(on, T, Cp)
        out_on = on(T, Cp)
        igg.record_event("chunk", chunk=0, step_begin=0, step_end=4,
                         ok=True, exec_s=0.01)
    finally:
        path = stop_flight_recorder()

    assert len(ir_on.permutes) == len(ir_off.permutes)
    assert len(ir_on.all_reduces) == len(ir_off.all_reduces) == 1
    assert not ir_on.all_gathers and not ir_on.all_to_alls
    assert len(ir_on.parameters()) == len(ir_off.parameters())
    for op in ("infeed", "outfeed"):
        assert ir_on.count(op) == ir_off.count(op) == 0
    for a, b in zip(out_on, out_off):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # ... and the trace really was live across the build + run
    evs = read_flight_events(path)
    stamped = [e for e in evs if e.get("kind") in ("run_begin", "chunk")]
    assert len(stamped) == 2
    assert all(e["trace_id"] == tr.trace_id
               and e["parent_span_id"] == tr.span_id for e in stamped)


def test_live_plane_leaves_chunk_program_untouched(tmp_path):
    """THE ISSUE-18 wire claim: the live observability plane is pure
    host-side tailing — building the guarded chunk runner while a
    flight recorder streams, a `LiveAggregate` incrementally tails the
    same file between chunks, an `AlertEngine` (default rule pack)
    evaluates every snapshot, and an `ObserveServer` answers
    ``/v1/observe`` + ``/v1/events`` over HTTP mid-run yields a program
    with identical collective counts and an identical fetch surface as
    with the plane off. Zero extra collectives, zero extra D2H fetches
    per chunk — the tail reads bytes from disk, never the device."""
    import json as _json
    import urllib.request

    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner
    from implicitglobalgrid_tpu.serve import ObserveServer
    from implicitglobalgrid_tpu.telemetry import (
        record_event, start_flight_recorder, stop_flight_recorder,
    )
    from implicitglobalgrid_tpu.telemetry.live import (
        AlertEngine, LiveAggregate,
    )

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=4,
                              key="hlo_live_off")
    ir_off = parse_program(off, T, Cp)

    jsonl = tmp_path / "flight_live.jsonl"
    start_flight_recorder(str(jsonl))
    live = LiveAggregate(str(jsonl))
    engine = AlertEngine()  # the default pack, observer-side
    try:
        with ObserveServer(str(tmp_path)) as obs:
            u = f"http://{obs.host}:{obs.port}"
            for i in range(3):  # the plane tails BETWEEN chunks
                record_event("chunk", chunk=i, step_begin=4 * i,
                             step_end=4 * i + 4, n=4, ok=True,
                             exec_s=0.01)
                live.poll()
                engine.evaluate(live.snapshot())
                with urllib.request.urlopen(u + "/v1/observe",
                                            timeout=10) as r:
                    _json.loads(r.read())
            on = make_guarded_runner(step, (3, 3), nt_chunk=4,
                                     key="hlo_live_on")
            ir_on = parse_program(on, T, Cp)
            out_on = on(T, Cp)
            with urllib.request.urlopen(
                    u + "/v1/events?since=-1&timeout_s=0.1",
                    timeout=10) as r:
                lines = [_json.loads(x) for x in r.read().splitlines()]
            assert any(e["kind"] == "chunk" for e in lines)
    finally:
        stop_flight_recorder()
    out_off = off(T, Cp)

    assert len(ir_on.permutes) == len(ir_off.permutes)
    assert len(ir_on.all_reduces) == len(ir_off.all_reduces) == 1
    assert not ir_on.all_gathers and not ir_on.all_to_alls
    # identical fetch surface: same program inputs and outputs
    assert len(ir_on.parameters()) == len(ir_off.parameters())
    for op in ("infeed", "outfeed"):
        assert ir_on.count(op) == ir_off.count(op) == 0
    assert len(out_on) == len(out_off) == 3  # T, Cp, stats vector


def test_reducers_share_the_guard_psum():
    """THE io wire claim (ISSUE 4): an enabled in-situ reducer set adds
    ZERO extra collectives to the chunk program — probe, axis slice and
    global min/max/mean/RMS segments concatenate into the health guard's
    single tiny all-reduce (one psum total, f32[2N + R] — exactly
    `guard_contract(N, R)`, the contract `run_resilient(audit=True)`
    checks), and the exchange's permute count is untouched."""
    from implicitglobalgrid_tpu.io.reducers import (
        AxisSlice, Probe, Stats, build_reducer_plan,
        make_reduced_post_chunk,
    )
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.models.common import make_state_runner
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    names = ("T", "Cp")
    reducers = [Probe("T", (0, 0, 0)), AxisSlice("T", 0, (0, 1, 1)),
                Stats("T")]
    plan = build_reducer_plan(reducers, names,
                              {"T": T, "Cp": Cp})
    guarded = make_guarded_runner(step, (3, 3), nt_chunk=2,
                                  key="hlo_io_plain")
    reduced = make_state_runner(
        step, (3, 3), nt_chunk=2, key=("hlo_io_red", plan.signature),
        post_chunk=make_reduced_post_chunk(names, plan))
    ir_g = parse_program(guarded, T, Cp)
    ir_r = parse_program(reduced, T, Cp)
    # the combined stats vector: 2 fields * 2 health entries + probe(1) +
    # slice(12: the implicit global x-size, 2*(8-2) periodic) + stats(2 +
    # 2*8 min/max slots) = 4 + 1 + 12 + 18 = 35 floats
    assert plan.length == 1 + 12 + 2 + 2 * 8
    _assert_honors(ir_g, guard_contract(len(names)))
    _assert_honors(ir_r, guard_contract(len(names), plan.length))
    assert len(ir_r.all_reduces) == len(ir_g.all_reduces) == 1
    assert len(ir_r.permutes) == len(ir_g.permutes)


def test_snapshot_writer_leaves_chunk_program_untouched(tmp_path):
    """Enabling snapshots adds ZERO collectives: with an ACTIVE
    SnapshotWriter (submitting, queue draining) the guarded chunk
    program compiles to identical collective counts and an identical
    fetch surface as with snapshots off — the writer only ever sees the
    host copies `submit` makes at chunk boundaries."""
    from implicitglobalgrid_tpu.io import SnapshotWriter
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    off = make_guarded_runner(step, (3, 3), nt_chunk=2, key="hlo_snap_off")
    ir_off = parse_program(off, T, Cp)
    with SnapshotWriter(tmp_path / "s") as w:
        w.submit({"T": T, "Cp": Cp}, 0)
        on = make_guarded_runner(step, (3, 3), nt_chunk=2,
                                 key="hlo_snap_on")
        ir_on = parse_program(on, T, Cp)
        w.flush(timeout=30.0)
    assert len(ir_on.permutes) == len(ir_off.permutes)
    assert len(ir_on.all_reduces) == len(ir_off.all_reduces) == 1
    assert len(ir_on.parameters()) == len(ir_off.parameters())
    for op in ("infeed", "outfeed"):
        assert ir_on.count(op) == ir_off.count(op) == 0


def test_permute_count_with_halowidth_2():
    """halowidth>1 exchanges still cost one pair per axis (slab width is
    static, not a per-row loop) — byte-audited: the hw=2 slabs carry
    exactly the plan's doubled wire bytes."""
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    args = _exchange_args((2, 2, 2), (12, 12, 12))
    contract = exchange_contract(*args)
    assert all(v["permutes"] == 2 for v in contract.axes.values())
    _assert_honors(_compiled_exchange(args), contract)


@pytest.mark.parametrize("model,impl", [
    ("diffusion3d", "xla"), ("acoustic3d", "xla"), ("stokes3d", "xla"),
    # the fused tier's fast tier-1 representative: same byte-exact
    # contract + crosscheck, via the canonical wire schema (the per-model
    # fused matrix rides the audit tests above / the slow tier)
    ("diffusion3d", "pallas_interpret"),
])
def test_audit_model_crosschecks_perfmodel(model, impl):
    """ISSUE-7 acceptance (extended to EVERY kernel tier): for each model
    family, the perf oracle's priced ppermute PAIRS and all-links wire
    bytes (`predict_step` over the tier's `StepWorkload.groups_for`
    rounds) EQUAL what the compiler actually emitted, per mesh axis, on
    the CPU mesh — static-model drift is a caught `perfmodel-drift`
    finding, not a silent mispricing. The same call also proves the
    plan-derived contract: slab-sized payloads on legal routes, exact
    per-axis counts, no gathers."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    rep = igg.audit_model(model, impl=impl)
    assert rep.ok, [f.to_json() for f in rep.findings]
    cc = rep.crosscheck
    assert cc is not None and cc["ok"]
    assert sorted(cc["axes"]) == ["gx", "gy", "gz"]
    for rec in cc["axes"].values():
        assert rec["modeled_pairs"] == rec["parsed_pairs"] > 0
        assert rec["modeled_wire_bytes"] == rec["parsed_wire_bytes"] > 0


@pytest.mark.slow
def test_audit_model_wire_dtype_self_contained(monkeypatch):
    """`audit_model(wire_dtype=...)` must apply the wire format to BOTH
    sides: the compile (scoped ``IGG_HALO_WIRE_DTYPE`` — the kwarg alone
    must produce a passing audit with nothing exported, and must never
    leak the reduced-precision mode into the process) and the
    expectation (contract payload dtypes, wire bytes, crosscheck
    pricing). On XLA:CPU — which normalizes bf16 payloads back to f32 in
    optimized HLO — the LOWERED module is audited instead, recorded in
    ``meta``, so the documented CLI exit-1 gate cannot false-fail."""
    import os

    monkeypatch.delenv("IGG_HALO_WIRE_DTYPE", raising=False)
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    rep = igg.audit_model("diffusion3d", wire_dtype="bfloat16")
    assert rep.ok, [f.to_json() for f in rep.findings]
    assert rep.crosscheck is not None and rep.crosscheck["ok"]
    assert rep.dialect == "stablehlo"
    assert "lowered_for_wire_audit" in rep.meta
    assert "IGG_HALO_WIRE_DTYPE" not in os.environ


@pytest.mark.slow
def test_audit_model_fused_fallback_contract_follows_xla_rounds():
    """REGRESSION (review finding): on a grid the fused kernel's
    eligibility gate rejects (halowidth != 1 — the deep-halo
    configuration), a Pallas request falls back to the XLA formulation;
    the contract must follow the FALLBACK's rounds (acoustic: V round +
    P round = 2 pairs/axis), not the requested fused grouping (1
    pair/axis) — else `tools audit` exit-1-fails a healthy program, the
    false-failure class the retired exemption existed to prevent."""
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    rep = igg.audit_model("acoustic3d", impl="pallas_interpret")
    assert rep.ok, [f.to_json() for f in rep.findings]
    assert rep.crosscheck is not None and rep.crosscheck["ok"]
    assert "rounds_impl" in rep.meta  # the fallback was recorded
    # XLA rounds: V round + P round -> 4 permutes per exchanging axis
    assert all(v["permutes"] == 4 for v in rep.contract.axes.values())


@pytest.mark.slow
def test_audit_model_fused_tier_has_real_contract():
    """REGRESSION (reversal of the PR-7 carve-out): `audit_model` on a
    fused Pallas impl used to SKIP the contract and crosscheck
    (`meta["contract_skipped"]`) because the fused kernels exchanged
    per-field in-kernel. The canonical wire schema retired that — the
    fused tier ships the same packed one-pair-per-axis wire the plan
    prices, so a Pallas audit must now carry a REAL byte-exact contract
    AND a passing perfmodel crosscheck, and the `tools audit` exit-1
    gate covers fused programs. (Fast representative:
    test_audit_model_crosschecks_perfmodel's pallas leg.)"""
    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    rep = igg.audit_model("acoustic3d", impl="pallas_interpret")
    assert rep.ok, [f.to_json() for f in rep.findings]
    assert rep.contract is not None
    assert rep.crosscheck is not None and rep.crosscheck["ok"]
    assert "contract_skipped" not in rep.meta
    # the fused pass packs all 4 fields into ONE round: 2 permutes/axis
    assert rep.collectives["permutes"] == 6
    assert all(v["permutes"] == 2 for v in rep.contract.axes.values())
