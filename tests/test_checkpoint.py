"""Checkpoint/resume tests (first-class subsystem here; the reference has
none — SURVEY §5.4, `gather!` is its only IO primitive)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)


def _init(**kw):
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True, **kw)


def test_save_restore_roundtrip(tmp_path):
    _init()
    p = str(tmp_path / "ckpt.npz")
    T = igg.device_put_g(np.arange(1000, dtype=np.float64).reshape(10, 10, 10))
    Cp = igg.ones_g()
    igg.save_checkpoint(p, {"T": T, "Cp": Cp}, step=42)
    state, step = igg.restore_checkpoint(p)
    assert step == 42
    assert np.array_equal(np.asarray(state["T"]), np.asarray(T))
    assert np.array_equal(np.asarray(state["Cp"]), np.asarray(Cp))
    # restored arrays carry the grid sharding (usable in update_halo directly)
    r = igg.update_halo(state["T"])
    assert np.asarray(r).shape == (10, 10, 10)


def test_resume_continues_simulation(tmp_path):
    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    _init()
    p = str(tmp_path / "ckpt.npz")
    T, Cp, prm = init_diffusion3d(dtype=np.float64)
    T10 = run_diffusion(T, Cp, prm, 10, nt_chunk=5)
    igg.save_checkpoint(p, {"T": T10, "Cp": Cp}, step=10)
    # resume and advance 5 more == straight 15
    state, step = igg.restore_checkpoint(p)
    T15_resumed = run_diffusion(state["T"], state["Cp"], prm, 5, nt_chunk=5)
    T15_straight = run_diffusion(T10, Cp, prm, 5, nt_chunk=5)
    assert np.allclose(np.asarray(T15_resumed), np.asarray(T15_straight),
                       rtol=0, atol=0)


def test_sharded_save_restore_roundtrip(tmp_path):
    """Pod-scale path: per-process shard files, restore by block coords —
    no host materializes the global state (round-3 verdict item 7)."""
    _init()
    d = str(tmp_path / "ckpt_dir")
    T = igg.device_put_g(np.arange(1000, dtype=np.float64).reshape(10, 10, 10))
    Cp = igg.ones_g(dtype=np.float32)
    igg.save_checkpoint_sharded(d, {"T": T, "Cp": Cp}, step=7)
    import os

    assert os.path.exists(os.path.join(d, "meta.npz"))
    assert os.path.exists(os.path.join(d, "shards_p0.npz"))
    state, step = igg.restore_checkpoint_sharded(d)
    assert step == 7
    assert np.array_equal(np.asarray(state["T"]), np.asarray(T))
    assert state["Cp"].dtype == np.float32
    assert np.array_equal(np.asarray(state["Cp"]), np.asarray(Cp))
    # restored arrays carry the grid sharding
    r = igg.update_halo(state["T"])
    assert np.asarray(r).shape == (10, 10, 10)

    # every block lives in the shard file, not a gathered array: the file
    # holds 8 blocks of 5x5x5 per array
    with np.load(os.path.join(d, "shards_p0.npz")) as z:
        tkeys = [k for k in z.files if k.startswith("__igg_arr__T__")]
        assert len(tkeys) == 8
        assert all(z[k].shape == (5, 5, 5) for k in tkeys)


def test_sharded_topology_mismatch_and_missing(tmp_path):
    _init()
    d = str(tmp_path / "ckpt_dir")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()})
    igg.finalize_global_grid()
    igg.init_global_grid(5, 5, 5, dimx=4, dimy=2, dimz=1, periodx=1,
                         quiet=True)
    with pytest.raises(IncoherentArgumentError, match="topology mismatch"):
        igg.restore_checkpoint_sharded(d)
    # strict=False is NOT an escape hatch here: blocks are keyed by the
    # saved decomposition (the single-file path reshards; this one cannot)
    with pytest.raises(IncoherentArgumentError, match="cannot reshard"):
        igg.restore_checkpoint_sharded(d, strict=False)
    with pytest.raises(InvalidArgumentError, match="meta not found"):
        igg.restore_checkpoint_sharded(str(tmp_path / "nope"))
    igg.finalize_global_grid()
    _init()
    with pytest.raises(InvalidArgumentError, match="'__'"):
        igg.save_checkpoint_sharded(d, {"bad__key": igg.ones_g()})


def test_sharded_stale_files_cleaned_and_ignored(tmp_path):
    """Leftover shard files from an earlier save with more processes must
    neither be read back (meta records the file count) nor survive a
    re-save (process 0 removes indices >= process_count)."""
    import os

    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()})
    stale = os.path.join(d, "shards_p7.npz")
    np.savez(stale, junk=np.zeros(3))
    st, _ = igg.restore_checkpoint_sharded(d)  # stale file ignored
    assert np.array_equal(np.asarray(st["A"]), np.ones((10, 10, 10)))
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()})  # re-save cleans
    assert not os.path.exists(stale)
    os.remove(os.path.join(d, "shards_p0.npz"))
    with pytest.raises(InvalidArgumentError, match="incomplete"):
        igg.restore_checkpoint_sharded(d)


def test_sharded_interrupted_save_detected(tmp_path):
    """A crash between one process's shard write and another's must not
    silently mix saves: every shard file carries the save token and
    restore validates it against meta."""
    import os
    import shutil

    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()}, step=1)
    old_shard = str(tmp_path / "old_shard.npz")
    shutil.copy(os.path.join(d, "shards_p0.npz"), old_shard)
    igg.save_checkpoint_sharded(d, {"A": igg.zeros_g()}, step=2)
    st, sp = igg.restore_checkpoint_sharded(d)
    assert sp == 2 and float(np.asarray(st["A"]).max()) == 0.0
    # simulate the crash: meta from save 2, shard file from save 1
    shutil.copy(old_shard, os.path.join(d, "shards_p0.npz"))
    with pytest.raises(IncoherentArgumentError, match="save-token"):
        igg.restore_checkpoint_sharded(d)


def test_sharded_checksum_detects_bitflip(tmp_path):
    """Per-file content checksums: a bit-flipped shard file must raise the
    typed corruption error on restore, never reassemble garbage."""
    import os

    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()}, step=1)
    path = os.path.join(d, "shards_p0.npz")
    assert os.path.exists(path + ".sha256")  # sidecar written by the save
    igg.corrupt_checkpoint(d, kind="bitflip", target="shard")
    with pytest.raises(IncoherentArgumentError, match="corrupt"):
        igg.restore_checkpoint_sharded(d)


def test_sharded_checksum_detects_truncation_and_meta_flip(tmp_path):
    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()}, step=1)
    igg.corrupt_checkpoint(d, kind="truncate", target="shard")
    with pytest.raises(IncoherentArgumentError, match="corrupt"):
        igg.restore_checkpoint_sharded(d)
    igg.finalize_global_grid()
    _init()
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()}, step=2)  # fresh dir
    st, sp = igg.restore_checkpoint_sharded(d)  # re-save replaced the dir
    assert sp == 2
    igg.corrupt_checkpoint(d, kind="bitflip", target="meta")
    with pytest.raises(IncoherentArgumentError, match="corrupt"):
        igg.restore_checkpoint_sharded(d)


def test_sharded_save_leaves_no_staging_dirs(tmp_path):
    """The atomic commit: after a save (including an overwrite) the parent
    holds exactly the checkpoint dir — no .tmp-/.old- staging leftovers."""
    import os

    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()}, step=1)
    igg.save_checkpoint_sharded(d, {"A": igg.zeros_g()}, step=2)
    assert sorted(os.listdir(tmp_path)) == ["ck"]
    st, sp = igg.restore_checkpoint_sharded(d)
    assert sp == 2 and float(np.asarray(st["A"]).max()) == 0.0


# ---------------------------------------------------------------------------
# Elastic restore: same implicit global grid, different decomposition
# ---------------------------------------------------------------------------

def _stacked_from_phys(P):
    """Independent construction of the stacked layout of physical field
    ``P`` on the LIVE grid (the `gather_interior` inverse): the elastic
    restore must be bit-identical to this."""
    gg = igg.global_grid()
    dims = [int(x) for x in gg.dims]
    n = [int(x) for x in gg.nxyz]
    ol = [int(x) for x in gg.overlaps]
    per = [int(x) for x in gg.periods]
    out = np.empty([dims[k] * n[k] for k in range(3)], P.dtype)
    for c in np.ndindex(*dims):
        idx = []
        for k in range(3):
            i = np.arange(n[k])
            if per[k]:
                idx.append((c[k] * (n[k] - ol[k]) + i - 1) % P.shape[k])
            else:
                idx.append(c[k] * (n[k] - ol[k]) + i)
        dst = tuple(slice(c[k] * n[k], (c[k] + 1) * n[k]) for k in range(3))
        out[dst] = P[np.ix_(*idx)]
    return out


@pytest.mark.parametrize("dims_a,dims_b", [
    ((2, 1, 1), (1, 2, 1)),
    ((2, 2, 1), (4, 1, 1)),
    ((2, 2, 2), (1, 1, 1)),
])
def test_elastic_restore_bit_identical_across_dims(tmp_path, dims_a, dims_b):
    """Save under one decomposition, restore under another: the restored
    STACKED state must be bit-identical to laying the same physical global
    field out over the new decomposition (block-coordinate reassembly
    end-to-end, mixed periodic/non-periodic axes, f64 + f32 fields)."""
    NG = (10, 10, 6)  # x,y non-periodic (interior 8 divides 1/2/4), z periodic

    def local_size(dims):
        return ((NG[0] - 2) // dims[0] + 2, (NG[1] - 2) // dims[1] + 2,
                NG[2] // dims[2] + 2)

    na = local_size(dims_a)
    igg.init_global_grid(*na, dimx=dims_a[0], dimy=dims_a[1],
                         dimz=dims_a[2], periodz=1, quiet=True)
    assert tuple(int(x) for x in igg.global_grid().nxyz_g) == NG
    rng = np.random.default_rng(7)
    P = rng.standard_normal(NG)
    Q = rng.standard_normal(NG).astype(np.float32)
    A = igg.device_put_g(_stacked_from_phys(P))
    B = igg.device_put_g(_stacked_from_phys(Q))
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": A, "B": B}, step=9)
    igg.finalize_global_grid()

    topo = igg.saved_topology(d)
    assert topo["step"] == 9
    nb = igg.elastic_local_size(topo, dims_b)
    assert nb == local_size(dims_b)
    igg.init_global_grid(*nb, dimx=dims_b[0], dimy=dims_b[1],
                         dimz=dims_b[2], periodz=1, quiet=True)
    state, step = igg.restore_checkpoint_elastic(d)
    assert step == 9
    assert state["B"].dtype == np.float32
    assert np.array_equal(np.asarray(state["A"]), _stacked_from_phys(P))
    assert np.array_equal(np.asarray(state["B"]),
                          _stacked_from_phys(Q).astype(np.float32))
    # and the physical field survives the round trip exactly
    assert np.array_equal(igg.gather_interior(state["A"]), P)


def test_elastic_restore_same_dims_delegates(tmp_path):
    _init()
    d = str(tmp_path / "ck")
    T = igg.device_put_g(np.arange(1000, dtype=np.float64).reshape(10, 10, 10))
    igg.save_checkpoint_sharded(d, {"T": T}, step=3)
    state, step = igg.restore_checkpoint_elastic(d)  # same grid: fast path
    assert step == 3
    assert np.array_equal(np.asarray(state["T"]), np.asarray(T))


def test_elastic_restore_rejects_incompatible(tmp_path):
    _init()
    d = str(tmp_path / "ck")
    igg.save_checkpoint_sharded(d, {"A": igg.ones_g()})
    topo = igg.saved_topology(d)
    # indivisible decomposition is rejected up front (periodic x interior
    # is 2*(5-2)=6 cells: 4 shards cannot split it evenly)
    with pytest.raises(IncoherentArgumentError, match="divide"):
        igg.elastic_local_size(topo, (4, 1, 1))
    # different overlaps on the live grid: only dims may change
    igg.finalize_global_grid()
    igg.init_global_grid(7, 7, 7, dimx=2, dimy=2, dimz=2, periodx=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    with pytest.raises(IncoherentArgumentError, match="overlaps"):
        igg.restore_checkpoint_elastic(d)


def test_load_without_grid(tmp_path):
    _init()
    p = str(tmp_path / "ckpt.npz")
    igg.save_checkpoint(p, {"A": igg.ones_g()})
    igg.finalize_global_grid()
    state, meta = igg.load_checkpoint(p)  # host-only read, no grid needed
    assert state["A"].shape == (10, 10, 10)
    assert list(meta["dims"]) == [2, 2, 2]
    assert meta["step"] is None


def test_topology_mismatch_rejected(tmp_path):
    _init()
    p = str(tmp_path / "ckpt.npz")
    igg.save_checkpoint(p, {"A": igg.ones_g()}, step=1)
    igg.finalize_global_grid()
    # same stacked shape, different topology (periods) ⇒ strict must reject
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    with pytest.raises(IncoherentArgumentError):
        igg.restore_checkpoint(p)
    # non-strict: caller takes responsibility; same stacked shape re-shards fine
    state, step = igg.restore_checkpoint(p, strict=False)
    assert step == 1
    assert np.asarray(state["A"]).shape == (10, 10, 10)


def test_atomic_overwrite_and_errors(tmp_path):
    _init()
    p = str(tmp_path / "ckpt.npz")
    igg.save_checkpoint(p, {"A": igg.ones_g()}, step=1)
    igg.save_checkpoint(p, {"A": igg.ones_g() * 2}, step=2)  # overwrite OK
    state, step = igg.restore_checkpoint(p)
    assert step == 2 and float(np.asarray(state["A"])[0, 0, 0]) == 2.0
    with pytest.raises(InvalidArgumentError):
        igg.save_checkpoint(p, {})
    with pytest.raises(InvalidArgumentError):
        igg.save_checkpoint(p, {"__igg_bad": igg.ones_g()})
    with pytest.raises(InvalidArgumentError):
        igg.restore_checkpoint(str(tmp_path / "missing.npz"))
