"""Ensemble-axis tests (ISSUE 12): batch E scenario members through one
mesh with collective counts flat in E.

THE claim under test: `make_state_runner(ensemble=E)` vmaps the member
axis over the chunk program, and jax's collective batching turns each
per-member collective into ONE op with an E x payload — so the compiled
exchange keeps exactly its solo permute count (byte-exact E-scaled
payloads, proven against the plan-derived contract), the health guard's
psum stays a single all-reduce of ``f32[E·(2N+R)]``, and each member's
trajectory is bit-identical to its solo run. Tier-1 keeps ONE fast
representative per behavior; E x policy sweeps ride the slow tier
(ROADMAP tier-1 wall-time budget). The per-member fault-isolation
representative lives in tests/test_resilience.py.
"""

import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.ensemble

_DATA = os.path.join(os.path.dirname(__file__), "data", "hlo")


def _diffusion(dtype=np.float32):
    from implicitglobalgrid_tpu.models import init_diffusion3d

    return init_diffusion3d(dtype=dtype)


# ---------------------------------------------------------------------------
# state construction + validation (no compiles)
# ---------------------------------------------------------------------------

def test_ensemble_state_layout_and_validation():
    """`ensemble_state` stacks a new leading member axis (replicated over
    the mesh — P(None, gx, gy, gz)), applies the deterministic perturb
    ramp with member 0 unperturbed, and every entry layer rejects
    ill-formed ensemble requests loudly."""
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.models import ensemble_state, run_diffusion
    from implicitglobalgrid_tpu.models.common import (
        ensemble_partition_spec, make_state_runner, resolve_ensemble_impl,
    )

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    T, Cp, p = _diffusion()
    E = 3
    ET = ensemble_state(T, E, perturb=0.5)
    assert tuple(ET.shape) == (E,) + tuple(T.shape)
    assert ET.sharding.spec == P(None, "gx", "gy", "gz")
    assert ensemble_partition_spec(2) == P(None, "gx", "gy")
    h = np.asarray(ET)
    base = np.asarray(T)
    assert np.array_equal(h[0], base)                    # member 0 = base
    assert np.allclose(h[2], base * 2.0, rtol=1e-6)      # 1 + 0.5*2
    # dict/tuple containers preserved
    d = ensemble_state({"T": T, "Cp": Cp}, E)
    assert set(d) == {"T", "Cp"} and d["T"].shape[0] == E
    # rejections: E < 1, Pallas impl, non-stacked state, bad leading dim
    with pytest.raises(InvalidArgumentError, match=">= 1"):
        ensemble_state(T, 0)
    with pytest.raises(InvalidArgumentError, match=">= 1"):
        make_state_runner(lambda s: s, (3,), nt_chunk=1, ensemble=0)
    with pytest.raises(InvalidArgumentError, match="XLA tier"):
        resolve_ensemble_impl("pallas")
    with pytest.raises(InvalidArgumentError, match="member axis"):
        run_diffusion(T, Cp, p, 2, ensemble=4)
    with pytest.raises(InvalidArgumentError, match="ensemble_state"):
        igg.run_resilient(lambda s: s, {"T": T}, 2, ensemble=4)
    # ProcessLoss under ensemble is ACCEPTED since ISSUE 14: the elastic
    # redistribution passes the member axis through untouched (the
    # end-to-end restart rides tests/test_reshard.py) — validation-level,
    # the machine constructs cleanly with the fault queued
    from implicitglobalgrid_tpu.runtime.driver import ResilientRun

    run = ResilientRun(lambda s: s, {"T": ET}, 4, igg.RunSpec(
        ensemble=E, faults=(igg.ProcessLoss(step=2, new_dims=(1, 2, 2)),)))
    run.close()


# ---------------------------------------------------------------------------
# THE tentpole: compiled collective count flat in E, byte-exact payloads
# ---------------------------------------------------------------------------

def test_ensemble_collectives_flat_in_E_byte_exact():
    """`audit_model(ensemble=8)` compiles the 8-member batched diffusion
    chunk and proves, on the OPTIMIZED program: identical per-axis
    permute counts to solo, payloads byte-exactly 8 x the solo plan
    (contract check), and the perf oracle's ensemble pricing equal to
    what the compiler emitted (crosscheck) — collective count flat in E,
    machine-verified end to end."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    rep1 = igg.audit_model("diffusion3d")
    rep8 = igg.audit_model("diffusion3d", ensemble=8)
    assert rep8.ok, [f.to_json() for f in rep8.findings]
    assert rep8.meta["ensemble"] == 8
    # flat in E: exactly the solo collective inventory
    assert rep8.collectives["permutes"] == rep1.collectives["permutes"]
    assert rep8.collectives["all_reduces"] == 0
    assert rep8.collectives["all_gathers"] == 0
    # byte-exact: 8x the solo wire, per axis and in total
    assert rep8.collectives["wire_bytes"] \
        == 8 * rep1.collectives["wire_bytes"] > 0
    for axis, exp in rep8.contract.axes.items():
        assert exp["permutes"] == rep1.contract.axes[axis]["permutes"]
        assert exp["wire_bytes"] \
            == 8 * rep1.contract.axes[axis]["wire_bytes"]
    cc = rep8.crosscheck
    assert cc is not None and cc["ok"] and cc["ensemble"] == 8
    for rec in cc["axes"].values():
        assert rec["modeled_pairs"] == rec["parsed_pairs"] > 0
        assert rec["modeled_wire_bytes"] == rec["parsed_wire_bytes"] > 0


def test_ensemble_guarded_chunk_single_batched_psum():
    """The guarded ensemble chunk still carries exactly ONE all-reduce —
    the batched ``f32[E·2N]`` stats — and its permute count equals the
    solo guarded chunk's (`guard_contract(..., ensemble=E)`, the same
    contract `run_resilient(audit=True)` checks for batched runs)."""
    from implicitglobalgrid_tpu.analysis import guard_contract, parse_program
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, ensemble_state,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    T, Cp, p = _diffusion()

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    E = 3
    solo = make_guarded_runner(step, (3, 3), nt_chunk=2, key="ens_g1")
    ens = make_guarded_runner(step, (3, 3), nt_chunk=2, key="ens_g3",
                              ensemble=E)
    ir_solo = parse_program(solo, T, Cp)
    ir_ens = parse_program(ens, ensemble_state(T, E), ensemble_state(Cp, E))
    assert len(ir_ens.permutes) == len(ir_solo.permutes)
    assert len(ir_ens.all_reduces) == 1
    pay = ir_ens.payload_of(ir_ens.all_reduces[0])
    assert pay.dtype == "f32" and pay.cells == E * 4
    findings = igg.check_contract(ir_ens, guard_contract(2, ensemble=E))
    assert findings == [], [f.to_json() for f in findings]


def test_ensemble_member_trajectories_bit_identical_to_solo():
    """Member 0 of a perturbed 4-member batch (perturb ramp leaves member
    0 at the base state) ends BIT-IDENTICAL to the solo run of the same
    steps, and perturbed members genuinely diverge — the vmapped chunk
    changes the economics, never the numerics."""
    from implicitglobalgrid_tpu.models import ensemble_state, run_diffusion

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    T, Cp, p = _diffusion(np.float64)
    E = 4
    ET = ensemble_state(T, E, perturb=0.01)
    ECp = ensemble_state(Cp, E)
    out = run_diffusion(ET, ECp, p, 6, nt_chunk=3, ensemble=E)
    ref = run_diffusion(T, Cp, p, 6, nt_chunk=3)
    h = np.asarray(out)
    assert tuple(out.shape) == (E,) + tuple(T.shape)
    assert np.array_equal(h[0], np.asarray(ref))
    assert not np.array_equal(h[1], h[0])


def test_ensemble_2d_checkpoint_roundtrip(tmp_path):
    """REGRESSION (review finding): restore used a rank heuristic that
    cannot tell a 2-D ensemble ``(E, x, y)`` from a solo 3-D field, so it
    sharded the member axis over ``gx`` and every wanted block key missed
    the saved set. The save now records each array's leading replicated
    (member) axes and restore rebuilds the TRUE sharding — round-trip
    bit-exact through both the plain and the elastic (same-dims
    delegation) paths; elastic onto DIFFERENT dims re-blocks the batched
    state too (ISSUE 14: the member axis passes through untouched)."""
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.models import ensemble_state

    igg.init_global_grid(6, 6, 1, dimx=4, dimy=2, dimz=1, quiet=True)
    T = igg.ones_g((6, 6), np.float32)
    E = 3
    ET = ensemble_state(T, E, perturb=0.5)
    d = str(tmp_path / "ck2d")
    igg.save_checkpoint_sharded(d, {"T": ET}, step=5)
    st, step = igg.restore_checkpoint_sharded(d)
    assert step == 5
    assert np.array_equal(np.asarray(st["T"]), np.asarray(ET))
    assert st["T"].sharding.spec == P(None, "gx", "gy")
    st2, _ = igg.restore_checkpoint_elastic(d)  # same-dims delegation
    assert np.array_equal(np.asarray(st2["T"]), np.asarray(ET))
    # a DIFFERENT decomposition of the same implicit global grid
    # (18, 10): the batched state re-blocks with the member axis passed
    # through — T is a per-member constant ramp, so every restored
    # member must be exactly its constant
    igg.finalize_global_grid()
    igg.init_global_grid(10, 4, 1, dimx=2, dimy=4, dimz=1, quiet=True)
    st3, _ = igg.restore_checkpoint_elastic(d)
    got = np.asarray(st3["T"])
    assert got.shape == (E, 20, 16)
    assert st3["T"].sharding.spec == P(None, "gx", "gy")
    for m in range(E):
        assert np.array_equal(
            got[m], np.full((20, 16), np.float32(1 + 0.5 * m)))


# ---------------------------------------------------------------------------
# quantized wire: per-member scale slabs (ISSUE 12 x ISSUE 9)
# ---------------------------------------------------------------------------

@pytest.mark.quant
def test_ensemble_quantized_wire_per_member_scales_roundtrip():
    """The quantized ensemble wire keeps PER-(member, slab) scales in the
    same scales-in-band layout: each member of the vmapped int8 exchange
    receives halos BIT-IDENTICAL to its own solo int8 exchange (the
    member's slabs quantize against the member's own max-abs scales —
    batching cannot launder one member's range into another's), and the
    plan prices E x the quantized payload including E x the scale tails
    behind the SAME pair count."""
    import jax
    import jax.numpy as jnp

    from implicitglobalgrid_tpu.models.common import (
        ensemble_partition_spec, ensemble_state,
    )
    from implicitglobalgrid_tpu.ops import halo as halo_mod
    from implicitglobalgrid_tpu.ops.precision import resolve_wire_dtype
    from implicitglobalgrid_tpu.utils.compat import shard_map

    igg.init_global_grid(4, 8, 8, dimx=8, dimy=1, dimz=1, periodx=1,
                         quiet=True)
    gg = igg.global_grid()
    E = 3
    rng = np.random.default_rng(7)
    A = igg.device_put_g(rng.normal(size=(32, 8, 8)).astype(np.float32))
    B = igg.device_put_g(rng.normal(size=(32, 8, 8)).astype(np.float32))
    wire = resolve_wire_dtype("int8")

    def exchange(*arrays):
        return tuple(halo_mod._exchange_arrays(
            gg, list(arrays), [gg.halowidths] * 2,
            halo_mod._normalize_dims_order(None), coalesce=True,
            wire=wire))

    espec = (ensemble_partition_spec(3),) * 2
    fn = jax.jit(shard_map(jax.vmap(exchange), mesh=gg.mesh,
                           in_specs=espec, out_specs=espec))
    # distinct member magnitudes: the per-member scales MUST differ
    EA = ensemble_state(A, E, perturb=10.0)
    EB = ensemble_state(B, E, perturb=10.0)
    out_a, out_b = fn(EA, EB)
    for m in range(E):
        solo_a, solo_b = igg.update_halo(
            jnp.asarray(EA[m]), jnp.asarray(EB[m]), wire_dtype="int8")
        assert np.array_equal(np.asarray(out_a)[m], np.asarray(solo_a)), m
        assert np.array_equal(np.asarray(out_b)[m], np.asarray(solo_b)), m
    # static pricing: same pairs, E x quantized bytes (scale tails incl.)
    solo_plan = igg.halo_comm_plan(A, B, wire_dtype="int8")
    ens_plan = igg.halo_comm_plan(A, B, wire_dtype="int8", ensemble=E)
    assert ens_plan["ppermutes"] == solo_plan["ppermutes"]
    assert ens_plan["wire_bytes"] == E * solo_plan["wire_bytes"]


# ---------------------------------------------------------------------------
# golden fixture (capture of the compiled ensemble exchange)
# ---------------------------------------------------------------------------

def test_ensemble_golden_fixture_honors_live_contract():
    """The checked-in optimized HLO of the E=4 two-field coalesced
    exchange (8-shard periodic ring) honors the LIVE plan-derived
    ensemble contract byte-exactly: one permute pair whose payloads are
    the member-batched ``f32[4,2,8,8]`` slabs — 4 x the solo bytes behind
    the solo pair count. Parser-level assertions on the same fixture live
    in tests/test_analysis.py (host-only, no grid)."""
    import jax

    from implicitglobalgrid_tpu.analysis import (
        check_contract, exchange_contract, parse_text,
    )

    with open(os.path.join(_DATA, "exchange_ensemble_coalesced.hlo.txt"),
              encoding="utf-8") as f:
        ir = parse_text(f.read())
    igg.init_global_grid(4, 8, 8, dimx=8, dimy=1, dimz=1, periodx=1,
                         quiet=True)
    args = [jax.ShapeDtypeStruct((32, 8, 8), np.float32),
            jax.ShapeDtypeStruct((32, 8, 8), np.float32)]
    contract = exchange_contract(*args, ensemble=4)
    assert contract.meta["ensemble"] == 4
    findings = check_contract(ir, contract)
    assert findings == [], [f.to_json() for f in findings]
    assert len(ir.permutes) == 2
    assert {ir.payload_of(p).dims for p in ir.permutes} == {(4, 2, 8, 8)}
    solo = exchange_contract(*args)
    assert contract.axes["gx"]["permutes"] == solo.axes["gx"]["permutes"]
    assert contract.axes["gx"]["wire_bytes"] \
        == 4 * solo.axes["gx"]["wire_bytes"]


# ---------------------------------------------------------------------------
# the service serves batched jobs (PR 8 rung d)
# ---------------------------------------------------------------------------

@pytest.mark.service
def test_scheduler_serves_batched_job(tmp_path):
    """An ensemble `JobSpec` (builtin_setup(ensemble=2) + RunSpec
    (ensemble=2)) runs to DONE under the scheduler: the result leads with
    the member axis, per-chunk reports carry member indices, and the
    job's scoped registry exposes per-member gauges
    (igg_member_rms{job=...,member=...})."""
    from implicitglobalgrid_tpu.service import JobSpec, MeshScheduler
    from implicitglobalgrid_tpu.service.job import builtin_setup

    E = 2
    spec = JobSpec(
        name="batched", setup=builtin_setup("diffusion3d", ensemble=E,
                                            perturb=0.1),
        nt=4, grid=dict(nx=6, ny=6, nz=6, dimx=2, dimy=2, dimz=1),
        run=igg.RunSpec(nt_chunk=2, key="ens_job", ensemble=E))
    with MeshScheduler(flight_dir=str(tmp_path)) as sched:
        sched.submit(spec)
        sched.run()
        job = sched.job("batched")
        assert job.state == "done", job.error
        assert tuple(job.result["T"].shape)[0] == E
        assert {r.member for r in job.reports} == {0, 1}
        fam = igg.metrics_registry().get("igg_job_member_rms")
        assert fam is not None
        labels = {(l.get("job"), l.get("member"), l.get("field"))
                  for l, _ in fam.samples()}
        assert ("batched", "0", "T") in labels
        assert ("batched", "1", "T") in labels


# ---------------------------------------------------------------------------
# slow tier: E x policy sweeps, CLI, other model families
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ensemble_flat_for_acoustic_and_stokes():
    """E-sweep across the other model families: the multi-field acoustic
    leapfrog (two exchange rounds) and the 8-field Stokes PT iteration
    keep their solo per-axis permute counts at E=4 with byte-exact
    4 x payloads (the fast diffusion representative runs in tier-1)."""
    igg.init_global_grid(8, 8, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    for model in ("acoustic3d", "stokes3d"):
        rep1 = igg.audit_model(model)
        rep4 = igg.audit_model(model, ensemble=4)
        assert rep4.ok, (model, [f.to_json() for f in rep4.findings])
        assert rep4.collectives["permutes"] == rep1.collectives["permutes"]
        assert rep4.collectives["wire_bytes"] \
            == 4 * rep1.collectives["wire_bytes"]
        assert rep4.crosscheck["ok"]


@pytest.mark.slow
def test_tools_audit_ensemble_cli():
    """`tools audit diffusion3d --ensemble 8 --cpu` exits 0 with a
    passing byte-exact contract + crosscheck (the operator-facing gate
    of the flat-in-E claim)."""
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "implicitglobalgrid_tpu.tools", "audit",
         "diffusion3d", "--ensemble", "8", "--cpu", "--nx", "8", "--json"],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json

    rep = json.loads(proc.stdout)
    assert rep["ok"] and rep["programs"][0]["meta"]["ensemble"] == 8


@pytest.mark.slow
def test_ensemble_predict_step_amortization_fields():
    """`predict_step(ensemble=E)` exposes the priced amortization the
    auto-tuner searches over: per_member_* fields, the solo anchor, and
    a ratio that IMPROVES with E in a latency-visible regime."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    T, Cp, _ = _diffusion()
    ratios = []
    for E in (2, 8, 16):
        pred = igg.predict_step("diffusion3d", (T, Cp), ensemble=E)
        assert pred["ensemble"] == E
        assert pred["per_member_step_s"] == pytest.approx(
            pred["step_s"] / E)
        assert pred["solo_step_s"] > 0
        ratios.append(pred["ensemble_amortization"])
    assert ratios[0] > ratios[1] > ratios[2]  # amortization grows with E
    solo = igg.predict_step("diffusion3d", (T, Cp))
    assert "per_member_step_s" not in solo and solo["ensemble"] == 1
