"""bench_util: the emit/device-tagging contract and the two-point
steady-state measurement (the method every bench rate flows through)."""

import sys

import numpy as np

sys.path.insert(0, "/root/repo")

import bench_util
import implicitglobalgrid_tpu as igg


def test_emit_tags_device_fields(capsys):
    row = bench_util.emit({"metric": "m", "value": 1.0, "unit": "u"})
    out = capsys.readouterr().out
    assert row["platform"] == "cpu" and row["n_devices"] >= 1
    assert '"metric": "m"' in out


def test_two_point_slope_and_fallback():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    try:
        calls = []

        def chunk(c):
            # work proportional to c, plus a fixed per-call cost
            import time

            calls.append(c)
            time.sleep(0.02 + 0.004 * c)

        s = bench_util.two_point(chunk, 5, 15, reps=1)
        # slope recovers the per-step cost, NOT the fixed 20ms/call part
        assert 0.002 < s < 0.008, s
        # warms both windows, then one timed run each
        assert calls == [5, 15, 5, 15]

        # non-positive slope falls back to the inclusive big-window rate
        def flat(c):
            import time

            time.sleep(0.01)

        s2 = bench_util.two_point(flat, 5, 15, reps=1)
        assert s2 > 0
    finally:
        igg.finalize_global_grid()
