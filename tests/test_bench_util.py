"""bench_util: the emit/device-tagging contract, the two-point
steady-state measurement (the method every bench rate flows through), and
the supervision layer (pid-stamped child marker, backend probe, automatic
--cpu fallback) added after the round-3 driver capture failed."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, "/root/repo")

import bench_util


def test_emit_tags_device_fields(capsys):
    row = bench_util.emit({"metric": "m", "value": 1.0, "unit": "u"})
    out = capsys.readouterr().out
    assert row["platform"] == "cpu" and row["n_devices"] >= 1
    assert '"metric": "m"' in out


def test_two_point_slope_and_fallback():
    calls = []

    def chunk(c):
        calls.append(c)

    def fake_timer(cost):
        # timer(fn) runs fn (one chunk call) and reports a deterministic
        # wall time for it — no real sleeps, so nothing to flake on.
        def timer(fn):
            fn()
            return cost(calls[-1])

        return timer

    # fixed 20ms/call + 4ms/step: the slope recovers exactly the per-step
    # cost, NOT the fixed part
    s = bench_util.two_point(chunk, 5, 15, reps=1,
                             timer=fake_timer(lambda c: 0.02 + 0.004 * c))
    assert abs(s - 0.004) < 1e-12, s
    assert bench_util.two_point.last["method"] == "two-point"
    # warms both windows, then one timed run each
    assert calls == [5, 15, 5, 15]

    # flat per-call time (t2 == t1) → inclusive big-window fallback, and
    # the .last record says so (ADVICE r3: emitted rows must be able to
    # distinguish the two semantics)
    calls.clear()
    s2 = bench_util.two_point(chunk, 5, 15, reps=1,
                              timer=fake_timer(lambda c: 0.01))
    assert abs(s2 - 0.01 / 15) < 1e-12
    assert bench_util.two_point.last["method"] == "inclusive-fallback"


def test_is_child_rejects_leaked_marker(monkeypatch):
    # round-3 failure mode: IGG_BENCH_CHILD present in the invoking
    # environment must NOT route the script down the unsupervised path —
    # not even "1" in a container where the parent IS pid 1
    monkeypatch.setenv("IGG_BENCH_CHILD", "1")
    assert not bench_util.is_child()
    monkeypatch.setenv("IGG_BENCH_CHILD", str(os.getppid()))
    assert not bench_util.is_child()  # pid alone is not enough
    # the real marker: supervising parent's pid + random token
    monkeypatch.setenv("IGG_BENCH_CHILD",
                       f"{os.getppid()}:deadbeefdeadbeef")
    assert bench_util.is_child()
    monkeypatch.delenv("IGG_BENCH_CHILD")
    assert not bench_util.is_child()


def test_probe_backend_ok_and_failure():
    # explicit-platform probe (the in-process config update — env-var
    # selection is overridden by the axon register on this image)
    assert bench_util.probe_backend(timeout=240, platform="cpu") is None
    err = bench_util.probe_backend(timeout=240, platform="bogus_platform")
    assert err is not None and "rc=" in err


@pytest.mark.slow
def test_run_with_retries_cpu_fallback(tmp_path):
    """End-to-end: backend probe fails → supervised rerun with --cpu →
    emitted row is tagged with the fallback note."""
    script = tmp_path / "fake_bench.py"
    script.write_text(textwrap.dedent("""
        import json, sys
        sys.path.insert(0, "/root/repo")
        import bench_util
        if bench_util.is_child():
            if "--cpu" not in sys.argv:
                sys.exit(1)  # accelerator path must not be reached
            print(json.dumps({"metric": "m", "value": 1.0, "unit": "u"}))
        else:
            # force the probe onto a nonexistent backend so the
            # tpu-unavailable path runs deterministically
            bench_util.run_with_retries("m", "u",
                                        probe_platform="bogus_platform")
    """))
    env = {k: v for k, v in os.environ.items() if k != "IGG_BENCH_CHILD"}
    env["IGG_BENCH_BUDGET"] = "600"
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-800:]
    rows = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    assert len(rows) == 1 and rows[0]["value"] == 1.0
    assert rows[0]["fallback"].startswith("tpu_unavailable")
