"""Benchmark: compile-time audit overhead of the supervised driver.

`run_resilient(audit=True)` (ISSUE 7) statically audits the chunk program
ONCE per run at compile time: trace + lower the already-built runner to
StableHLO, parse it into `analysis.ProgramIR`, and check the guard
contract + implicit-grid lints — all host-side, before the first dispatch
(the HLO-level guarantee that the chunk PROGRAM is untouched lives in
tests/test_hlo_audit.py). This leg bounds that one-time cost against the
<2%-of-run gate (ISSUE 7 acceptance):

- ``value`` (gated): the DETERMINISTIC accounting — the directly-timed
  cost of the exact `audit_chunk_program` call the driver makes (min of
  several reps: the trace/lower/parse work is pure host compute), over
  the median audit-off run time. One-time cost, so the fraction SHRINKS
  as runs get longer; this measures it at the bench's operating point.
- ``ab_median_frac`` (corroboration): end-to-end audit-on vs audit-off
  `run_resilient` A/B — alternating-order interleaved pairs, median of
  the per-pair fractional differences, same estimator as the other
  overhead legs (on the shared CPU mesh the run jitter sits far above
  the signal; the figure corroborates, the accounting gates).

Usage: python bench_audit.py          (real chip)
       python bench_audit.py --cpu    (8-device virtual CPU mesh)
"""

import os
import sys

import bench_util


def audit_overhead_rows(nx: int, nt_chunk: int, n_chunks: int = 3,
                        reps: int = 10):
    """One row on the CURRENT grid (caller owns init/finalize): the
    compile-time audit's cost as a fraction of a supervised run."""
    import statistics
    import time

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.analysis import audit_chunk_program
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    state = {"T": T, "Cp": Cp}
    nt = nt_chunk * n_chunks
    key = ("bench_audit", nx, nt_chunk)

    def run(audit):
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key,
                          audit=audit)

    run(False)  # warm: compile once (shared key)
    run(True)

    # --- end-to-end A/B (corroboration) --------------------------------
    times = {"off": [], "on": []}
    pair_fracs = []
    for r in range(reps):
        order = [(False, "off"), (True, "on")] if r % 2 == 0 \
            else [(True, "on"), (False, "off")]
        d = {}
        for audit, slot in order:
            igg.tic()
            run(audit)
            d[slot] = igg.toc()
            times[slot].append(d[slot])
        pair_fracs.append((d["on"] - d["off"]) / d["off"])

    # --- deterministic accounting (the gated figure) -------------------
    # the EXACT call the driver makes once per run, on the same guarded
    # runner the run dispatches; min-of-reps because trace+lower+parse is
    # pure host compute and the minimum is the cost, the rest scheduler
    # noise
    def tup_step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    runner = make_guarded_runner(tup_step, (3, 3), nt_chunk=nt_chunk,
                                 key=("bench_audit_probe", nx, nt_chunk))
    audit_s, report = None, None
    for _ in range(3):
        t0 = time.monotonic()
        report = audit_chunk_program(runner, (T, Cp), names=("T", "Cp"))
        dt = time.monotonic() - t0
        audit_s = dt if audit_s is None else min(audit_s, dt)
    t_off_med = statistics.median(times["off"])

    return [{
        "metric": "audit_overhead_frac",
        "value": audit_s / t_off_med,
        "unit": "fraction of run time, one-time compile-boundary audit "
                "(target < 0.02)",
        "target": 0.02,
        "nt": nt,
        "nt_chunk": nt_chunk,
        "audit_s": audit_s,
        "audit_ok": bool(report.ok),
        "audit_findings": len(report.findings),
        "off_run_s_median": t_off_med,
        "on_run_s_median": statistics.median(times["on"]),
        "ab_median_frac": statistics.median(pair_fracs),
        "note": "one-time cost per run (trace+lower+parse+check, zero "
                "backend compiles): the gated fraction shrinks with run "
                "length; ab_median_frac corroborates from the end-to-end "
                "A/B under shared-CPU jitter",
    }]


def run_audit_overhead(dims, cpu: bool):
    """The canonical leg: init its own grid over ``dims``, measure,
    finalize, return the rows. Shared by this script's __main__ and
    `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    nx, nt_chunk = (32, 60) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return audit_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_audit_overhead(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("audit_overhead_frac", "fraction")
