"""Measure the closed-loop auto-tuner (ISSUE 13): search wall time and the
tuned-vs-default guarantee.

`telemetry.tune_config` searches `predict_step` over per-axis
``comm_every`` x wire precision x coalescing (x overlap x ensemble) and
validates the top candidates with short measured calibration runs. Two
properties ride the perf gates:

- ``tuned_vs_default_speedup`` — measured default-config step time over
  the measured winner's (ABSOLUTE gate >= 1.0: the all-defaults baseline
  is always in the measured candidate set, so the tuner can surface a
  win but can never ship a regression);
- ``tune_search_s`` — the whole search's wall time (pricing every
  candidate + the measured validation runs), the cost a job pays once
  per (model, mesh) to stop hand-setting env vars.

Usage: python bench_tune.py --cpu   (8-device virtual mesh)
       python bench_tune.py         (real devices)
"""

from __future__ import annotations

import sys

import bench_util


def run_tune_rows(dims, cpu: bool):
    """The canonical leg (shared with bench_all.py — config in ONE
    place): a measured diffusion3D tune on a small latency-leaning grid
    over cadence {1, 2, z:2} candidates."""
    from implicitglobalgrid_tpu.telemetry import tune_config

    nx = 24 if cpu else 64
    grid = dict(nx=nx, ny=nx, nz=nx, dimx=dims[0], dimy=dims[1],
                dimz=dims[2], periodx=1, periody=1, periodz=1)
    cfg = tune_config("diffusion3d", grid, None, measure=True, top_k=2,
                      comm_every_options=("1", "2", "z:2"))
    return [
        {
            "metric": "tuned_vs_default_speedup",
            "value": cfg.speedup,
            "unit": "measured default step_s / tuned step_s (>= 1.0 by "
                    "construction: the default is always in the "
                    "measured set)",
            "winner": cfg.knobs(),
            "measured_step_s": cfg.measured_step_s,
            "baseline_step_s": cfg.baseline_step_s,
            "predicted_step_s": cfg.predicted_step_s,
            "candidates_priced": cfg.meta["priced"],
            "candidates_measured": cfg.meta["measured"],
        },
        {
            "metric": "tune_search_s",
            "value": cfg.meta["search_s"],
            "unit": "s wall (price every candidate + measured top-k "
                    "validation, min-of-3 windows)",
        },
    ]


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    dims = tuple(int(d) for d in igg.dims_create(len(jax.devices()),
                                                 (0, 0, 0)))
    for row in run_tune_rows(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("tuned_vs_default_speedup", "t1/t2")
