"""Benchmark: health-guard overhead of the resilient runtime.

The driver (`runtime/driver.py`) fuses a per-chunk health probe into the
compiled chunk program (`runtime/health.py`): per field a non-finite count
and a norm accumulator, reduced with ONE tiny psum and fetched once per
chunk boundary. This leg measures what that supervision costs at the
driver's operating point — a full guarded chunk (probe + fetch included)
against the plain chunk of `make_state_runner` — as a fraction of step
time. Target: < 2% (`ISSUE` acceptance; the HLO-side guarantee of exactly
one extra small collective is tested in tests/test_hlo_audit.py).

Note the measurement is INCLUSIVE single-chunk timing, not the two-point
slope: the guard is a per-chunk fixed cost, which a slope over two window
sizes would cancel out by construction.

Prints one JSON row (plus per-config rows when run through bench_all).

Usage: python bench_resilience.py          (real chip)
       python bench_resilience.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import sys

import bench_util


def guard_overhead_rows(nx: int, nt_chunk: int, reps: int = 12):
    """One row: guarded vs plain chunk time on the CURRENT grid (caller
    owns init/finalize). ``value`` is the fractional per-step overhead of
    supervision at chunk size ``nt_chunk`` — probe compute, the one
    psum, and the driver's per-chunk stats fetch all included."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.models.common import make_state_runner
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    plain = make_state_runner(step, (3, 3), nt_chunk=nt_chunk,
                              key=("bench_resil", nx, nt_chunk))
    guarded = make_guarded_runner(step, (3, 3), nt_chunk=nt_chunk,
                                  key=("bench_resil", nx, nt_chunk))

    # The A/B isolates the guard's MARGINAL cost: both sides run inside a
    # tic/toc window whose closing barrier performs the identical drain,
    # so `guarded` pays exactly its extras — in-chunk probe, the one
    # psum, the driver's tiny stats fetch — on top of the same chunk.
    def run_plain():
        plain(T, Cp)  # drained by toc's barrier

    def run_guarded():
        np.asarray(guarded(T, Cp)[-1])  # the driver's per-chunk fetch

    def run_chunked_style():
        igg.sync(plain(T, Cp))  # what run_chunked does per chunk call

    # Interleaved reps (back-to-back blocks would fold machine drift into
    # the tiny difference); min is the estimator the rest of the suite
    # uses (`bench_util.two_point`), median emitted alongside since the
    # per-call jitter of the shared-CPU mesh (±15% observed) is an order
    # of magnitude above the guard cost being bounded.
    import statistics

    run_plain()
    run_guarded()
    run_chunked_style()  # warm: compile + first dispatch outside windows
    times = {"p": [], "g": [], "s": []}
    for _ in range(reps):
        for fn, slot in ((run_plain, "p"), (run_guarded, "g"),
                         (run_chunked_style, "s")):
            igg.tic()
            fn()
            times[slot].append(igg.toc())
    t_plain, t_guarded, t_sync = (min(times[s]) for s in "pgs")
    frac = (t_guarded - t_plain) / t_plain
    med = {s: statistics.median(times[s]) for s in "pgs"}
    return [{
        "metric": "resilience_guard_overhead_frac",
        "value": frac,
        "unit": "fraction of plain chunk time (target < 0.02)",
        "target": 0.02,
        "nt_chunk": nt_chunk,
        "plain_chunk_s": t_plain,
        "guarded_chunk_s": t_guarded,
        "median_overhead_frac": (med["g"] - med["p"]) / med["p"],
        # the driver's fetch REPLACES run_chunked's separate sync-drain
        # program; vs that baseline supervision is usually free or better
        "sync_drain_chunk_s": t_sync,
        "vs_run_chunked_frac": (t_guarded - t_sync) / t_sync,
    }]


def run_guard_overhead(dims, cpu: bool):
    """The canonical leg: init its own grid over ``dims``, measure,
    finalize, return the rows. Shared by this script's __main__ and
    `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    # the guard is a per-chunk FIXED cost: the chunk must be long enough
    # that single-call jitter (multi-% on the shared-CPU mesh) does not
    # swamp the sub-1% signal being bounded
    nx, nt_chunk = (32, 100) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return guard_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_guard_overhead(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries(
            "resilience_guard_overhead_frac", "fraction")
