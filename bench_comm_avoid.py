"""Measure communication-avoiding deep-halo stepping (`comm_every=k`).

Same wire bytes per physical step, 1/k the collectives: this harness runs
the SAME implicit global grid at k=1 and k=2 (local sizes chosen so the
global grids match — the trajectories are bit-identical, proven by
tests/test_comm_avoid.py) and reports per-PHYSICAL-step wall time plus
trace-derived exposed-collective time for each cadence.

Emits ONE JSON line:
  {"metric": "comm_avoid_speedup", "value": t_k1/t_k2, ...}

Usage: python bench_comm_avoid.py --cpu   (8-device virtual mesh)
       python bench_comm_avoid.py         (real devices)
"""

from __future__ import annotations

import sys
import tempfile

import bench_util


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_diffusion3d, make_run, make_run_deep,
    )

    from implicitglobalgrid_tpu.models.common import resolve_comm_every

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    # small local blocks: the latency-bound regime deep halos target
    base = 32 if cpu else 64
    steps = 24 if cpu else 120  # physical steps per chunk window

    def measure(k, init_fn, runner_fn, trace_exposed=False, hw=None):
        """One cadence-A/B leg: same implicit global grid at every
        cadence (periodic: dims*(n-ol) must match -> n_d = base +
        2(hw_d-1) with per-dim halo depth hw_d, default the cadence's
        own k_d; the Stokes PT scheme needs hw=2k per axis), two-point
        windows over super-steps, optional exposed-collective trace
        (max over planes, the bench_weak.py statistic). ``k`` may be a
        per-axis cadence spec ("z:2")."""
        cad = resolve_comm_every(k)
        K = cad.cycle
        if hw is None:
            hw = tuple(cad.for_dim(d) for d in range(3))
        elif not hasattr(hw, "__len__"):
            hw = (hw,) * 3
        n = tuple(base + 2 * (h - 1) for h in hw)
        igg.init_global_grid(n[0], n[1], n[2], dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=1, periody=1, periodz=1,
                             overlaps=tuple(2 * h for h in hw),
                             halowidths=tuple(hw),
                             quiet=True)
        try:
            state, p = init_fn(k)
            sup = steps // K  # super-steps per window

            def chunk(c):
                igg.sync(runner_fn(p, c, k)(*state))

            sec_per_super = bench_util.two_point(chunk, sup, 3 * sup)
            cells = (float(igg.nx_g()) * float(igg.ny_g())
                     * float(igg.nz_g()))
            row = {"k": k, "local_n": n if len(set(n)) > 1 else n[0],
                   "step_ms": sec_per_super / K * 1e3,
                   "cell_updates_per_s": cells / (sec_per_super / K)}
            if trace_exposed:
                row["exposed_comm_ms_per_step"] = None
                try:
                    run = runner_fn(p, sup, k)
                    igg.sync(run(*state))
                    with tempfile.TemporaryDirectory() as d:
                        with igg.trace(d):
                            igg.sync(run(*state))
                        stats = igg.overlap_stats(d)
                    if stats:
                        row["exposed_comm_ms_per_step"] = max(
                            s["exposed_comm_us"] for s in stats.values()
                        ) / steps / 1e3
                except Exception:
                    pass
            return row
        finally:
            igg.finalize_global_grid()

    def diff_init(k):
        T, Cp, p = init_diffusion3d(dtype=np.float32, comm_every=k)
        return (T, Cp), p

    def diff_runner(p, c, k):
        return (make_run_deep(p, c) if resolve_comm_every(k).deep
                else make_run(p, c, impl="xla"))

    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, make_acoustic_run, make_acoustic_run_deep,
    )

    def ac_init(k):
        return init_acoustic3d(dtype=np.float32, comm_every=k)

    def ac_runner(p, c, k):
        return (make_acoustic_run_deep(p, c) if resolve_comm_every(k).deep
                else make_acoustic_run(p, c, impl="xla"))

    from implicitglobalgrid_tpu.models import (
        init_stokes3d, make_stokes_run, make_stokes_run_deep,
    )

    def st_init(k):
        return init_stokes3d(dtype=np.float32, comm_every=k)

    def st_runner(p, c, k):
        return (make_stokes_run_deep(p, c) if resolve_comm_every(k).deep
                else make_stokes_run(p, c, impl="xla"))

    def per_axis_model_row():
        """The ISSUE 13 rescue row, MODELED (`predict_step` —
        deterministic): the recorded LOSING small-block Stokes config vs
        the z-only cadence on the same implicit global grid. Uniform
        k=2 pays 2k-wide slabs (block growth + 3.5x x/y wire) on EVERY
        axis; z:2 pays them on z alone while amortizing exactly the
        link class whose latency hurts. Profile = THIS mesh's class of
        compute/ICI coefficients (the emulated-mesh defaults the
        measured rows above run on) with the z axis crossing a
        DCN-class link (10 GB/s, ~200 us collective launch — the
        cross-pod regime COMM_AVOID's note names as the cadence's
        break-even; bench_quant.py models the bandwidth-starved DCN
        story where `wire_dtype="z:int8"` is the lever instead — the
        auto-tuner searches the two knobs jointly). Expected shape:
        uniform < 1 (the recorded loss persists), per-axis > 1 (the
        rescue)."""
        import jax as _jax
        from implicitglobalgrid_tpu.telemetry.perfmodel import (
            MachineProfile, predict_step,
        )
        from implicitglobalgrid_tpu.telemetry.tune import _MODEL_STAGGER

        profile = MachineProfile(
            membw_GBps=6.0, flops_G=6.0,
            axes={"gx": {"GBps": 4.0, "latency_s": 3e-5},
                  "gy": {"GBps": 4.0, "latency_s": 3e-5},
                  "gz": {"GBps": 10.0, "latency_s": 2e-4}},
            source="default", device={"platform": "model:mesh+dcn-z"})
        stagger = _MODEL_STAGGER["stokes3d"]  # canonical state layout
        nb = 24  # small latency-bound blocks (the losing config's regime)

        def price(ce, hw):
            n = tuple(nb - 2 + 2 * h for h in hw)
            igg.init_global_grid(n[0], n[1], n[2], dimx=dims[0],
                                 dimy=dims[1], dimz=dims[2], periodx=1,
                                 periody=1, periodz=1,
                                 overlaps=tuple(2 * h for h in hw),
                                 halowidths=tuple(hw), quiet=True)
            try:
                gg = igg.global_grid()
                gd = tuple(int(d) for d in gg.dims)
                fields = tuple(
                    (_jax.ShapeDtypeStruct(
                        tuple(gd[d] * (n[d] + offs[d]) for d in range(3)),
                        np.float32), tuple(hw))
                    for offs in stagger)
                return predict_step("stokes3d", fields, profile=profile,
                                    comm_every=ce)["step_s"]
            finally:
                igg.finalize_global_grid()

        t1 = price(1, (1, 1, 1))
        t2u = price(2, (4, 4, 4))
        t2z = price("z:2", (2, 2, 4))
        return {
            "stokes_per_axis_model_speedup": t1 / t2z,
            "stokes_uniform_model_speedup": t1 / t2u,
            "model_step_s": {"k1": t1, "k2_uniform": t2u, "z2": t2z},
            "model_note": ("predict_step on the ICI+DCN hierarchical "
                           "profile: the z-only cadence amortizes the "
                           "DCN axis's latency without the uniform "
                           "scheme's all-axes slab-compute penalty — "
                           "the recorded losing config wins per-axis"),
        }

    r1 = measure(1, diff_init, diff_runner, trace_exposed=True)
    r2 = measure(2, diff_init, diff_runner, trace_exposed=True)
    a1 = measure(1, ac_init, ac_runner)
    a2 = measure(2, ac_init, ac_runner)
    s1 = measure(1, st_init, st_runner)
    s2 = measure(2, st_init, st_runner, hw=4)
    # the per-axis rescue, MEASURED on this mesh: z-only cadence pays
    # radius-2 halos (hw 2) on x/y and 4-wide on z only — less slab
    # compute than the uniform row, so it must land above the recorded
    # 0.51x even where the (latency-free) emulated mesh can't make it
    # an outright win
    s2z = measure("z:2", st_init, st_runner, hw=(2, 2, 4))
    bench_util.emit({
        "metric": "comm_avoid_speedup",
        "value": r1["step_ms"] / r2["step_ms"],
        "unit": "step_ms(k=1)/step_ms(k=2), same global grid",
        "k1": r1,
        "k2": r2,
        "acoustic_k1": a1,
        "acoustic_k2": a2,
        "acoustic_speedup": a1["step_ms"] / a2["step_ms"],
        "stokes_k1": s1,
        "stokes_k2": s2,
        "stokes_speedup": s1["step_ms"] / s2["step_ms"],
        "stokes_z2": s2z,
        "stokes_per_axis_speedup": s1["step_ms"] / s2z["step_ms"],
        **per_axis_model_row(),
        "note": ("deep-halo stepping: k-wide exchange every k steps — "
                 "same wire bytes, 1/k collectives (for the leapfrog one "
                 "4-field round replaces the base scheme's 2k per-step "
                 "V + P rounds). Trajectories: diffusion/acoustic "
                 "bit-identical, Stokes ~1-ulp-equal on XLA:CPU "
                 "(radius-2 scheme, 2k-deep halos, 7-field exchange — "
                 "see StokesParams docstring; tests/test_comm_avoid.py). "
                 "Small-block latency-bound config on purpose; the "
                 "uniform Stokes rows record a LOSING configuration "
                 "(compute-heavy iteration vs all-axes doubled slab "
                 "width) — the PER-AXIS z:2 rows (ISSUE 13) are the "
                 "rescue: measured above the uniform row here, and an "
                 "outright win on the modeled ICI+DCN profile where the "
                 "amortized axis actually carries DCN latency"),
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("comm_avoid_speedup", "t1/t2")
