"""Measure communication-avoiding deep-halo stepping (`comm_every=k`).

Same wire bytes per physical step, 1/k the collectives: this harness runs
the SAME implicit global grid at k=1 and k=2 (local sizes chosen so the
global grids match — the trajectories are bit-identical, proven by
tests/test_comm_avoid.py) and reports per-PHYSICAL-step wall time plus
trace-derived exposed-collective time for each cadence.

Emits ONE JSON line:
  {"metric": "comm_avoid_speedup", "value": t_k1/t_k2, ...}

Usage: python bench_comm_avoid.py --cpu   (8-device virtual mesh)
       python bench_comm_avoid.py         (real devices)
"""

from __future__ import annotations

import sys
import tempfile

import bench_util


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_diffusion3d, make_run, make_run_deep,
    )

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    # small local blocks: the latency-bound regime deep halos target
    base = 32 if cpu else 64
    steps = 24 if cpu else 120  # physical steps per chunk window

    def measure(k, init_fn, runner_fn, trace_exposed=False, hw=None):
        """One cadence-A/B leg: same implicit global grid at every k
        (periodic: dims*(n-ol) must match -> n_k = base + 2(hw-1) with
        halo depth hw, default k; the Stokes PT scheme needs hw=2k),
        two-point windows over super-steps, optional exposed-collective
        trace (max over planes, the bench_weak.py statistic)."""
        hw = k if hw is None else hw
        n = base + 2 * (hw - 1)
        igg.init_global_grid(n, n, n, dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=1, periody=1, periodz=1,
                             overlaps=(2 * hw,) * 3, halowidths=(hw,) * 3,
                             quiet=True)
        try:
            state, p = init_fn(k)
            sup = steps // k  # super-steps per window

            def chunk(c):
                igg.sync(runner_fn(p, c, k)(*state))

            sec_per_super = bench_util.two_point(chunk, sup, 3 * sup)
            cells = (float(igg.nx_g()) * float(igg.ny_g())
                     * float(igg.nz_g()))
            row = {"k": k, "local_n": n,
                   "step_ms": sec_per_super / k * 1e3,
                   "cell_updates_per_s": cells / (sec_per_super / k)}
            if trace_exposed:
                row["exposed_comm_ms_per_step"] = None
                try:
                    run = runner_fn(p, sup, k)
                    igg.sync(run(*state))
                    with tempfile.TemporaryDirectory() as d:
                        with igg.trace(d):
                            igg.sync(run(*state))
                        stats = igg.overlap_stats(d)
                    if stats:
                        row["exposed_comm_ms_per_step"] = max(
                            s["exposed_comm_us"] for s in stats.values()
                        ) / steps / 1e3
                except Exception:
                    pass
            return row
        finally:
            igg.finalize_global_grid()

    def diff_init(k):
        T, Cp, p = init_diffusion3d(dtype=np.float32, comm_every=k)
        return (T, Cp), p

    def diff_runner(p, c, k):
        return make_run_deep(p, c) if k > 1 else make_run(p, c, impl="xla")

    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, make_acoustic_run, make_acoustic_run_deep,
    )

    def ac_init(k):
        return init_acoustic3d(dtype=np.float32, comm_every=k)

    def ac_runner(p, c, k):
        return (make_acoustic_run_deep(p, c) if k > 1
                else make_acoustic_run(p, c, impl="xla"))

    from implicitglobalgrid_tpu.models import (
        init_stokes3d, make_stokes_run, make_stokes_run_deep,
    )

    def st_init(k):
        return init_stokes3d(dtype=np.float32, comm_every=k)

    def st_runner(p, c, k):
        return (make_stokes_run_deep(p, c) if k > 1
                else make_stokes_run(p, c, impl="xla"))

    r1 = measure(1, diff_init, diff_runner, trace_exposed=True)
    r2 = measure(2, diff_init, diff_runner, trace_exposed=True)
    a1 = measure(1, ac_init, ac_runner)
    a2 = measure(2, ac_init, ac_runner)
    s1 = measure(1, st_init, st_runner)
    s2 = measure(2, st_init, st_runner, hw=4)
    bench_util.emit({
        "metric": "comm_avoid_speedup",
        "value": r1["step_ms"] / r2["step_ms"],
        "unit": "step_ms(k=1)/step_ms(k=2), same global grid",
        "k1": r1,
        "k2": r2,
        "acoustic_k1": a1,
        "acoustic_k2": a2,
        "acoustic_speedup": a1["step_ms"] / a2["step_ms"],
        "stokes_k1": s1,
        "stokes_k2": s2,
        "stokes_speedup": s1["step_ms"] / s2["step_ms"],
        "note": ("deep-halo stepping: k-wide exchange every k steps — "
                 "same wire bytes, 1/k collectives (for the leapfrog one "
                 "4-field round replaces the base scheme's 2k per-step "
                 "V + P rounds). Trajectories: diffusion/acoustic "
                 "bit-identical, Stokes ~1-ulp-equal on XLA:CPU "
                 "(radius-2 scheme, 2k-deep halos, 7-field exchange — "
                 "see StokesParams docstring; tests/test_comm_avoid.py). "
                 "Small-block latency-bound config on purpose; the "
                 "Stokes rows record a LOSING configuration (compute-"
                 "heavy iteration vs doubled slab width)"),
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("comm_avoid_speedup", "t1/t2")
