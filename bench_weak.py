"""Weak-scaling efficiency harness (BASELINE.json north star:
">=90% parallel efficiency at v5p-256 vs single chip").

Weak scaling: the per-device local block stays fixed while the device count
grows; efficiency = t(1 device) / t(N devices) for the same per-device work.
The reference's headline claim is the near-flat weak-scaling curve on
thousands of GPUs (`reference README.md:6-8`).

With one real TPU chip this harness cannot measure true multi-chip scaling;
it runs the SAME code path (per-axis ppermute exchange over the mesh) on the
virtual CPU mesh to validate the harness end-to-end. Virtual CPU devices
share host cores, so the printed efficiency UNDERSTATES real hardware — on a
pod, point it at the real devices (no --cpu) and the number is the real one.

Usage: python bench_weak.py --cpu [--devices N]   (virtual mesh harness)
       python bench_weak.py                       (real devices, needs >1 chip)
       add --strong for STRONG scaling (fixed global size, shrinking blocks)
"""

from __future__ import annotations

import json
import sys

import bench_util


def main() -> None:
    cpu = "--cpu" in sys.argv
    n_req = None
    if "--devices" in sys.argv:
        n_req = int(sys.argv[sys.argv.index("--devices") + 1])
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_req or 8}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    devices = jax.devices()
    n = n_req or len(devices)
    if n < 2:
        strong_early = "--strong" in sys.argv
        print(json.dumps({
            "metric": ("strong" if strong_early else "weak")
                      + "_scaling_efficiency",
            "value": None,
            "unit": "rateN/(N*rate1)" if strong_early else "t1/tN",
            "note": "needs >1 device; run with --cpu for the virtual-mesh harness",
        }))
        return

    local_n, nt = (48, 60) if cpu else (256, 600)
    chunk = max(1, nt // 6)

    strong = "--strong" in sys.argv

    def measure(nd, block):
        import tempfile

        dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
        igg.init_global_grid(block[0], block[1], block[2],
                             dimx=dims[0], dimy=dims[1], dimz=dims[2],
                             periodx=1, periody=1, periodz=1,
                             devices=devices[:nd], quiet=True)
        T, Cp, p = init_diffusion3d(dtype=np.float32)
        run_diffusion(T, Cp, p, chunk, nt_chunk=chunk)   # warm
        igg.tic()
        out = run_diffusion(T, Cp, p, nt, nt_chunk=chunk)
        t = igg.toc(sync_on=out)
        # Exposed-collective time per step off a short trace of the SAME
        # warmed chunk program (round-4 verdict: each curve point must
        # separate exposed-collective growth — what ICI determines on
        # hardware — from core contention, which only compresses compute).
        exposed_ms = None
        try:
            with tempfile.TemporaryDirectory() as d:
                with igg.trace(d):
                    igg.sync(run_diffusion(T, Cp, p, chunk, nt_chunk=chunk))
                stats = igg.overlap_stats(d)
            if stats:
                # MAX over planes, not sum: devices run the same SPMD
                # program ~in lockstep, so per-device exposed time is the
                # critical path — a sum would scale with plane count and
                # fabricate growth on real multi-plane captures (the CPU
                # fallback returns one aggregate entry either way)
                exposed_ms = max(
                    s["exposed_comm_us"] for s in stats.values()
                ) / chunk / 1e3
        except Exception:
            pass  # a failed trace must not void the timing measurement
        igg.finalize_global_grid()
        return t, exposed_ms

    # device counts for the CURVE (the reference's headline artifact is a
    # weak-scaling efficiency curve, `reference README.md:6-8`): powers of
    # two up to n, always including n. On REAL hardware only the {1, n}
    # endpoints run (full-size nt-step measurements at every power of two
    # would not fit the supervised attempt budget — bench_util's parent
    # would kill the child and silently downgrade the artifact to the CPU
    # fallback); the cheap virtual-mesh (--cpu) runs record the full curve.
    Ns = sorted({1} | ({2 ** k for k in range(1, 10) if 2 ** k <= n}
                      if cpu else set()) | {n})

    if strong:
        # STRONG scaling: fixed global work, local blocks shrink PER AXIS
        # by that axis' device count (the global grid stays ~fixed up to
        # the implicit-size overlap terms); efficiency on per-cell rates:
        # eff = rate_N_total / (N * rate_1).
        t1, ex1 = measure(1, (local_n,) * 3)
        r1 = local_n ** 3 * nt / t1
        curve = [{"n": 1, "t_s": round(t1, 4), "efficiency": 1.0,
                  "exposed_comm_ms_per_step": ex1}]
        for nd in Ns[1:]:
            nd_dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
            block_n = tuple(max(8, local_n // d) for d in nd_dims)
            tn, exn = measure(nd, block_n)
            rn = int(np.prod(block_n)) * nd * nt / tn
            curve.append({"n": nd, "t_s": round(tn, 4),
                          "local_block": list(block_n),
                          "efficiency": rn / (r1 * nd),
                          "exposed_comm_ms_per_step": exn})
        bench_util.emit({
            "metric": "strong_scaling_efficiency",
            "value": curve[-1]["efficiency"],
            "unit": f"rateN/(N*rate1), N={n}",
            "curve": curve,
            "note": ("virtual CPU mesh (devices share host cores; "
                     "understates real hardware)" if cpu else "real devices"),
        })
        return

    t1, ex1 = measure(1, (local_n,) * 3)
    curve = [{"n": 1, "t_s": round(t1, 4), "efficiency": 1.0,
              "exposed_comm_ms_per_step": ex1}]
    for nd in Ns[1:]:
        tn, exn = measure(nd, (local_n,) * 3)
        curve.append({"n": nd, "t_s": round(tn, 4), "efficiency": t1 / tn,
                      "exposed_comm_ms_per_step": exn})
    eff = curve[-1]["efficiency"]
    bench_util.emit({
        "metric": "weak_scaling_efficiency",
        "value": eff,
        "unit": f"t1/t{n}",
        "vs_baseline": eff / 0.90,   # north star: >=0.90 at scale
        "curve": curve,
        "note": (("virtual CPU mesh: devices SHARE host cores, so t_s "
                  "growth is mostly compute contention (8 virtual devices "
                  "on one socket) and the efficiency number does not "
                  "transfer to hardware; exposed_comm_ms_per_step is the "
                  "transferable part — comm time with the whole pool "
                  "idle, the analog of ICI-exposed time on a pod")
                 if cpu else "real devices"),
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    elif "--strong" in sys.argv:
        bench_util.run_with_retries("strong_scaling_efficiency",
                                    "rateN/(N*rate1)")
    else:
        bench_util.run_with_retries("weak_scaling_efficiency", "t1/tN")
