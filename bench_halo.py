"""Benchmark: `update_halo` effective GB/s per chip.

First metric of BASELINE.json ("update_halo! effective GB/s/chip"); the
reference claims "halo updates close to hardware limit" qualitatively
(`reference README.md:10,30`) with no number published.

Accounting (effective-bandwidth convention): per exchanged dimension, each
chip sends 2 slabs and receives 2 slabs of ``hw x plane`` cells, i.e.
``bytes/call = sum_dims 4 * hw * plane_cells * itemsize``. Periodic on all
dims so every chip exchanges on every side (single chip: the self-neighbor
local-copy path, the reference's 1-process test technique).

The timed region runs the exchanges INSIDE one compiled program
(`lax.fori_loop` of `local_update_halo` under `shard_map`) — how the
framework actually uses halo exchange in a hot loop — so per-dispatch host
latency is excluded, exactly like the reference measures `update_halo!`
inside its running time loop.

Prints ONE JSON line.

Usage: python bench_halo.py          (real chip, f32, 512^3 local)
       python bench_halo.py --cpu    (small smoke run on virtual CPU mesh)
"""

from __future__ import annotations

import sys

import bench_util


def _pack_roundtrip_step(gg):
    """A `local_update_halo`-shaped program with the ppermutes REPLACED BY
    IDENTITY: per dim, the same canonical-schema pack -> unpack -> deliver
    pipeline the coalesced exchange runs (`ops.wire`), minus the wire.
    Timing it attributes the coalesced exchange's cost between pack/unpack
    work and the collectives themselves — the attribution the perfdb gate
    watches so a future PACK-bound regression (the 0.75x 8-field episode
    this PR fixes) is caught as `update_halo_pack_frac_*` drift, not by
    eyeballing BENCH_ALL."""
    from jax import lax

    from implicitglobalgrid_tpu.ops.halo import (
        DEFAULT_DIMS_ORDER, _check_slab_fit, _dim_meta,
    )
    from implicitglobalgrid_tpu.ops.wire import slab_schema

    def step(arrays):
        arrays = list(arrays)
        for dim in DEFAULT_DIMS_ORDER:
            D, periodic, disp = _dim_meta(gg, dim)
            if D == 1:
                # mirror `_coalesce_groups`: self-neighbor axes are
                # per-field local swaps with NO pack on the live path —
                # packing them here would overstate pack_frac on meshes
                # with singleton axes
                continue
            sends_r, sends_l, metas = [], [], []
            for a in arrays:
                hw = int(gg.halowidths[dim])
                s = a.shape[dim]
                ol_d = int(gg.overlaps[dim] + (s - gg.nxyz[dim]))
                _check_slab_fit(s, dim, ol_d, hw)
                sends_r.append(lax.slice_in_dim(a, s - ol_d, s - ol_d + hw,
                                                axis=dim))
                sends_l.append(lax.slice_in_dim(a, ol_d - hw, ol_d,
                                                axis=dim))
                metas.append((hw, s))
            schema = slab_schema(dim, [x.shape for x in sends_r],
                                 arrays[0].dtype)
            recv_l = schema.unpack(schema.pack(sends_r))  # wire = identity
            recv_r = schema.unpack(schema.pack(sends_l))
            for k, a in enumerate(arrays):
                hw, s = metas[k]
                a = lax.dynamic_update_slice_in_dim(a, recv_l[k], 0,
                                                    axis=dim)
                arrays[k] = lax.dynamic_update_slice_in_dim(
                    a, recv_r[k], s - hw, axis=dim)
        return tuple(arrays)

    return step


def coalescing_ab_rows(nx: int, c1: int, field_counts=(2, 4, 8, 16),
                       dtype=None):
    """A/B + attribution rows for the coalesced multi-field exchange.

    For each field count N, times the N-field `local_update_halo` hot loop
    with collective coalescing ON (one ppermute pair per axis) and OFF
    (2·N permutes per axis) on the CURRENT grid, plus the PACK-ROUNDTRIP
    program (same schema pack/unpack/deliver, identity wire). Returns two
    rows per N: the A/B ``update_halo_coalesced_speedup_{N}fields``
    (value = per_field_s / coalesced_s, >1 means coalescing wins) and the
    attribution ``update_halo_pack_frac_{N}fields`` (value = pack-roundtrip
    share of the coalesced call — the perfdb gate flags it rising, i.e. a
    pack-bound regression, independent of scheduler noise in the A/B).
    Caller owns grid init/finalize."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models.common import make_state_runner

    dtype = dtype or np.float32
    gg = igg.global_grid()
    rows = []
    for n_fields in field_counts:
        fields = tuple(igg.ones_g((nx, nx, nx), dtype) * (i + 1)
                       for i in range(n_fields))
        secs = {}
        pack_step = _pack_roundtrip_step(gg)
        modes = (("coalesced", True), ("per_field", False),
                 ("pack_roundtrip", None))
        for mode, co in modes:
            if co is None:
                def step(s):
                    return pack_step(s)
            else:
                def step(s, co=co):
                    out = igg.local_update_halo(*s, coalesce=co)
                    return out if isinstance(out, tuple) else (out,)

            def chunk(c):
                run = make_state_runner(
                    step, (3,) * n_fields, nt_chunk=c,
                    key=("bench_halo_ab", mode, n_fields, nx, str(dtype)))
                igg.sync(run(*fields))

            # reps=4 (min-kept): the contended shared-core mesh injects
            # scheduler spikes into individual windows; the min over four
            # is the same contention-robust estimator `calibrate_machine`
            # uses, and the A/B ratio is only meaningful between two
            # uncontended draws
            secs[mode] = bench_util.two_point(chunk, c1, 3 * c1, reps=4)
        rows.append({
            "metric": f"update_halo_coalesced_speedup_{n_fields}fields",
            "value": secs["per_field"] / secs["coalesced"],
            "unit": "x (per_field_s / coalesced_s)",
            "coalesced_s_per_call": secs["coalesced"],
            "per_field_s_per_call": secs["per_field"],
        })
        rows.append({
            "metric": f"update_halo_pack_frac_{n_fields}fields",
            "value": secs["pack_roundtrip"] / secs["coalesced"],
            "unit": "frac (pack+unpack+deliver share of coalesced call)",
            "pack_roundtrip_s_per_call": secs["pack_roundtrip"],
            "permute_attributed_s_per_call": max(
                0.0, secs["coalesced"] - secs["pack_roundtrip"]),
        })
    return rows


def run_coalescing_ab(dims, cpu: bool):
    """The canonical A/B leg: init its own all-periodic grid over ``dims``,
    measure, finalize, return the rows. Shared by this script's __main__
    and `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    # c_ab=8 (was 4): the A/B slope at 32^3 is dispatch-overhead-bound and
    # the contended shared-core mesh swings short chunks by tens of
    # percent — longer two-point chunks cut the draw-to-draw scatter
    nx_ab, c_ab = (32, 8) if cpu else (256, 20)
    igg.init_global_grid(nx_ab, nx_ab, nx_ab, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return coalescing_ab_rows(nx_ab, c_ab)
    finally:
        igg.finalize_global_grid()


def staged_ab_rows(nx: int, c1: int, devices_per_granule: int,
                   n_fields: int = 2):
    """Topology-staged wire rows on the CURRENT two-granule grid (ISSUE
    16; caller owns init/finalize and the IGG_TPU_DCN_GRANULES scope):

    - ``staged_dcn_msgs_ratio`` — static, from `halo_comm_plan`'s staged
      detail: flat per-DCN-link message count / staged (= the ICI gather
      fold). Gated absolute >= devices_per_granule/2 under
      IGG_BENCH_STRICT (``staged_msgs_gate_ok``).
    - ``update_halo_staged_vs_flat_speedup`` — measured flat/staged loop
      seconds. The emulated CPU mesh has no DCN to save, so this is the
      staging-overhead gate in disguise; the modeled row prices the win.
    """
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models.common import make_state_runner

    fields = tuple(igg.ones_g((nx, nx, nx), np.float32) * (i + 1)
                   for i in range(n_fields))
    plan = igg.halo_comm_plan(*fields, wire_stage="z:staged")
    det = plan["axes"].get("gz", {}).get("staged")
    if det is None:
        return [{
            "metric": "staged_dcn_msgs_ratio", "value": None,
            "note": "no staged layout on this mesh (z granules "
                    "undeclared or no perpendicular ICI gather axis); "
                    "rows skipped",
        }]
    ratio = det["flat_dcn_pairs"] / det["dcn_pairs"]
    secs = {}
    for mode, ws in (("flat", None), ("staged", "z:staged")):
        def step(s, ws=ws):
            out = igg.local_update_halo(*s, wire_stage=ws)
            return out if isinstance(out, tuple) else (out,)

        def chunk(c):
            run = make_state_runner(
                step, (3,) * n_fields, nt_chunk=c,
                key=("bench_halo_staged", mode, n_fields, nx))
            igg.sync(run(*fields))

        secs[mode] = bench_util.two_point(chunk, c1, 3 * c1, reps=4)
    gate = ratio >= devices_per_granule / 2.0
    return [
        {
            "metric": "staged_dcn_msgs_ratio",
            "value": ratio,
            "unit": "x (flat DCN-crossing pairs / staged, per round — "
                    "the per-DCN-link message-count fold)",
            "dcn_pairs": det["dcn_pairs"],
            "flat_dcn_pairs": det["flat_dcn_pairs"],
            "fold": det["fold"],
            "gather_axis": det["gather_axis"],
        },
        {
            "metric": "staged_msgs_gate_ok",
            "value": 1.0 if gate else 0.0,
            "unit": f"bool (1 = fold >= devices_per_granule/2 = "
                    f"{devices_per_granule / 2.0:g})",
        },
        {
            "metric": "update_halo_staged_vs_flat_speedup",
            "value": secs["flat"] / secs["staged"],
            "unit": "x (flat_s / staged_s per exchange-loop call)",
            "flat_s_per_call": secs["flat"],
            "staged_s_per_call": secs["staged"],
            "note": "the emulated CPU mesh has no DCN link to save: this "
                    "is the staging-overhead gate; staged_model_speedup "
                    "prices the on-wire win",
        },
    ]


def staged_model_row(dims_s):
    """The staged-vs-flat step speedup, MODELED (`predict_step` —
    deterministic): diffusion3D at production-scale blocks on the canned
    hierarchical ICI+DCN profile (`hierarchical_machine_profile` — the
    COMM_AVOID.json regime), z staged over 2 granules. The caller scopes
    IGG_TPU_DCN_GRANULES; nothing is allocated."""
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.telemetry.perfmodel import (
        hierarchical_machine_profile,
    )

    profile = hierarchical_machine_profile()
    nx = 256
    igg.init_global_grid(nx, nx, nx, dimx=dims_s[0], dimy=dims_s[1],
                         dimz=dims_s[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        stacked = tuple(nx * d for d in dims_s)
        T = jax.ShapeDtypeStruct(stacked, np.float32)
        Cp = jax.ShapeDtypeStruct(stacked, np.float32)
        flat = igg.predict_step("diffusion3d", (T, Cp), profile=profile)
        staged = igg.predict_step("diffusion3d", (T, Cp), profile=profile,
                                  wire_stage="z:staged")
        verdict = staged["comm"].get("gz", {}).get("staged", {})
        return {
            "metric": "staged_model_speedup",
            "value": flat["step_s"] / staged["step_s"],
            "unit": "x (flat step_s / staged step_s, modeled on the "
                    "hierarchical ICI+DCN profile)",
            "flat_step_s": flat["step_s"],
            "staged_step_s": staged["step_s"],
            "staged_axis_wins": bool(verdict.get("wins", False)),
            "staged_axis_s": verdict.get("staged_s"),
            "flat_axis_s": verdict.get("flat_s"),
        }
    finally:
        igg.finalize_global_grid()


def run_staged_ab(dims, cpu: bool):
    """The topology-staged wire leg (ISSUE 16) on a TWO-GRANULE mesh: z
    split into 2 DCN granules (scoped ``IGG_TPU_DCN_GRANULES=z:2``) with
    the remaining devices forming the perpendicular ICI gather axis.
    Shared by this script's __main__ and `bench_all.py` so the config
    stays in ONE place."""
    import os

    import implicitglobalgrid_tpu as igg

    nd = dims[0] * dims[1] * dims[2]
    if nd < 4:
        return [{
            "metric": "staged_dcn_msgs_ratio", "value": None,
            "note": f"{nd} device(s) cannot form a two-granule mesh with "
                    "an ICI gather axis; rows skipped",
        }]
    dims_s = (nd // 2, 1, 2)  # z = the DCN axis, x = the gather axis
    nx_ab, c_ab = (32, 8) if cpu else (256, 20)
    saved = os.environ.get("IGG_TPU_DCN_GRANULES")
    os.environ["IGG_TPU_DCN_GRANULES"] = "z:2"
    try:
        igg.init_global_grid(nx_ab, nx_ab, nx_ab, dimx=dims_s[0],
                             dimy=dims_s[1], dimz=dims_s[2], periodx=1,
                             periody=1, periodz=1, quiet=True)
        try:
            rows = staged_ab_rows(nx_ab, c_ab,
                                  devices_per_granule=nd // 2)
        finally:
            igg.finalize_global_grid()
        rows.append(staged_model_row(dims_s))
    finally:
        if saved is None:
            os.environ.pop("IGG_TPU_DCN_GRANULES", None)
        else:
            os.environ["IGG_TPU_DCN_GRANULES"] = saved
    return rows


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg

    if cpu:
        nx, c1 = 64, 5
        dims = (2, 2, 2)
    else:
        nx, c1 = 512, 60
        nd = len(jax.devices())
        dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))

    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    hw = [int(h) for h in gg.halowidths]
    A = igg.ones_g((nx, nx, nx), np.float32)

    from implicitglobalgrid_tpu.models.common import make_state_runner

    def chunk(c):
        run = make_state_runner(lambda s: (igg.local_update_halo(s[0]),),
                                (3,), nt_chunk=c, key="bench_halo")
        igg.sync(run(A))

    s = bench_util.two_point(chunk, c1, 3 * c1)

    itemsize = 4
    planes = [nx * nx] * 3  # local plane cells per dim (cubic block)
    bytes_per_call = sum(4 * hw[d] * planes[d] * itemsize for d in range(3))
    gbps = bytes_per_call / s / 1e9
    # No published reference number exists (BASELINE.md: qualitative claim
    # only); vs_baseline is vs 1 GB/s/chip as a nominal floor.
    bench_util.emit({
        "metric": "update_halo_effective_GBps_per_chip",
        "value": gbps,
        "unit": "GB/s/chip",
        "vs_baseline": gbps / 1.0,
    })

    igg.finalize_global_grid()

    # Coalesced vs per-field A/B (2/4/8 fields) on its own grid — the
    # multi-field leg `bench_all.py` also records into BENCH_ALL.json.
    for row in run_coalescing_ab(dims, cpu):
        bench_util.emit(row)

    # Topology-staged wire A/B + modeled speedup on a two-granule mesh
    # (ISSUE 16) — also recorded by `bench_all.py`.
    for row in run_staged_ab(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries(
            "update_halo_effective_GBps_per_chip", "GB/s/chip"
        )
