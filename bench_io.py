"""Benchmark: async snapshot overhead + speedup vs gather-per-snapshot.

The io pipeline's perf claims (ISSUE 4 acceptance):

- ``io_snapshot_overhead_frac`` (gated < 0.02): what enabling async
  snapshots adds to a supervised run. The only step-loop-blocking work is
  `SnapshotWriter.submit` — the device->host copy of this process's shard
  blocks plus the enqueue; serialization/fsync/commit runs on the writer
  thread under the next chunk. Like bench_telemetry, the gated figure is
  DETERMINISTIC accounting: the microbenchmarked submit cost times the
  snapshots a run takes, over the run's median snapshot-off time — the
  end-to-end A/B (alternating interleaved pairs) corroborates on the
  noisy shared-CPU mesh rather than resolving the sub-1% signal.
- ``io_async_vs_gather_speedup``: the same output cadence done the
  legacy way — `gather_interior` to the root + a synced `np.save` at
  every snapshot step, serialized INTO the run — versus the async
  pipeline. The recorded value is the STEADY-STATE accounting
  (run + n*measured gather+write) / (run + n*measured submit): what each
  path costs the step loop per cadence once the terminal drain is
  amortized (a long run drains once; this 1-2 s bench run would charge
  it every rep, and fsync latency on the shared filesystem swings by
  >10x rep to rep — the measured on-run median is attached for
  corroboration). On a single-host CPU mesh the gather is a local
  device_get, so the figure understates the multi-host win, where the
  gather is an O(global) DCN collective on every process.

Usage: python bench_io.py          (real chip)
       python bench_io.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import itertools
import os
import shutil
import sys
import tempfile

import bench_util


def snapshot_overhead_rows(nx: int, nt_chunk: int, n_chunks: int = 3,
                           reps: int = 8):
    """Rows on the CURRENT grid (caller owns init/finalize)."""
    import statistics
    import time

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.io.snapshot import SnapshotWriter
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    state = {"T": T, "Cp": Cp}
    nt = nt_chunk * n_chunks
    key = ("bench_io", nx, nt_chunk)
    tmp = tempfile.mkdtemp(prefix="igg_bench_io_")
    seq = itertools.count()

    def run_off():
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key)

    def run_on():
        # snapshot only T — the same single field the gather baseline
        # writes, so the two paths move comparable bytes
        d = os.path.join(tmp, f"snaps{next(seq)}")
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key,
                          snapshot_dir=d, snapshot_every=nt_chunk,
                          snapshot_fields=("T",))

    # warm: compile once (shared key), one committed snapshot set
    run_off()
    run_on()

    # --- end-to-end A/B (corroboration) --------------------------------
    times = {"off": [], "on": []}
    pair_fracs = []
    for r in range(reps):
        order = [(run_off, "off"), (run_on, "on")] if r % 2 == 0 \
            else [(run_on, "on"), (run_off, "off")]
        d = {}
        for fn, slot in order:
            igg.tic()
            fn()
            d[slot] = igg.toc()
            times[slot].append(d[slot])
        pair_fracs.append((d["on"] - d["off"]) / d["off"])
    pair_fracs.sort()
    iqr = (pair_fracs[(3 * len(pair_fracs)) // 4]
           - pair_fracs[len(pair_fracs) // 4])
    t_off_med = statistics.median(times["off"])
    t_on_med = statistics.median(times["on"])

    # --- deterministic accounting (the gated figure) -------------------
    # submit = D2H of this process's shard blocks + enqueue: the ONLY
    # work the step loop waits on; everything else overlaps on the
    # writer thread. Probe it directly with a deep queue (no disk wait),
    # drain outside the timed window.
    n_probe = 30
    w = SnapshotWriter(os.path.join(tmp, "probe"),
                       queue_depth=n_probe + 1, policy="block",
                       fields=("T",))
    t0 = time.monotonic()
    for i in range(n_probe):
        w.submit(state, i)
    per_submit_s = (time.monotonic() - t0) / n_probe
    w.close(timeout=120.0)
    accounted = per_submit_s * n_chunks / t_off_med

    # --- synchronous gather-per-snapshot baseline ----------------------
    # the legacy output path, serialized into the run: gather_interior to
    # the root + a synced np.save, once per snapshot step
    def gather_write(i):
        G = igg.gather_interior(state["T"])
        path = os.path.join(tmp, f"gather_{i}.npy")
        with open(path, "wb") as f:
            np.save(f, G)
            f.flush()
            os.fsync(f.fileno())

    gather_write(-1)  # warm the transfer path
    g_times = []
    for i in range(5):
        t0 = time.monotonic()
        gather_write(i)
        g_times.append(time.monotonic() - t0)
    t_gather = statistics.median(g_times)
    sync_run_s = t_off_med + n_chunks * t_gather
    async_run_s = t_off_med + n_chunks * per_submit_s
    speedup = sync_run_s / async_run_s

    shutil.rmtree(tmp, ignore_errors=True)
    return [{
        "metric": "io_snapshot_overhead_frac",
        "value": accounted,
        "unit": "fraction of run time, deterministic submit accounting "
                "(target < 0.02)",
        "target": 0.02,
        "nt": nt,
        "nt_chunk": nt_chunk,
        "snapshots_per_run": n_chunks,
        "per_submit_s": per_submit_s,
        "off_run_s_median": t_off_med,
        "on_run_s_median": t_on_med,
        "ab_median_frac": statistics.median(pair_fracs),
        "ab_noise_iqr": iqr,
        "note": "submit (D2H + enqueue) is the only step-loop-blocking "
                "cost of async snapshots; the A/B corroborates under "
                "shared-CPU jitter",
    }, {
        "metric": "io_async_vs_gather_speedup",
        "value": speedup,
        "unit": "x (sync gather_interior+save per snapshot / async "
                "SnapshotWriter submit, steady-state accounting)",
        "gather_write_s_median": t_gather,
        "per_submit_s": per_submit_s,
        "sync_run_s": sync_run_s,
        "async_run_s_accounted": async_run_s,
        "on_run_s_median_measured": t_on_med,
        "note": "steady-state: terminal-drain amortized (a long run "
                "drains once; this short bench would charge it every "
                "rep under >10x fsync jitter). Single-host CPU gather is "
                "a local device_get — multi-host runs pay an O(global) "
                "DCN collective instead, so the figure is a floor",
    }]


def run_io_overhead(dims, cpu: bool):
    """The canonical leg: init its own grid over ``dims``, measure,
    finalize, return the rows. Shared by this script's __main__ and
    `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    nx, nt_chunk = (32, 60) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return snapshot_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_io_overhead(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("io_snapshot_overhead_frac", "fraction")
