"""3-D staggered-grid acoustic wave on the implicit global grid.

The BASELINE weak-scaling workload (config 4): leapfrog pressure/velocity
updates on a staggered grid — the model family the reference's companion
ParallelStencil miniapps cover (`reference README.md:10` cites the same
multi-physics app family). Demonstrates staggered fields (Vx is
``(nx+1, ny, nz)``), the fused Pallas step+exchange tier, and the
`hide_communication` overlap option of the XLA tier.

Run:  python examples/acoustic3D_multixpu.py [--cpu] [--xla]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_acoustic3d, run_acoustic


def acoustic3D():
    cpu = "--cpu" in sys.argv
    nx = 32 if cpu else 192
    nt = 60 if cpu else 600
    impl = "xla" if "--xla" in sys.argv else None  # None -> kernel tier on TPU
    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        nx, nx, nx, periodx=1, periody=1, periodz=1)

    # Gaussian pressure pulse at the domain center; velocities at rest.
    state, p = init_acoustic3d(dtype=np.float32, overlap=impl == "xla")

    chunk = max(1, nt // 10)
    run_acoustic(state, p, chunk, nt_chunk=chunk, impl=impl)  # warm
    igg.tic()
    state = run_acoustic(state, p, nt, nt_chunk=chunk, impl=impl)
    t = igg.toc(sync_on=state[0])

    P = igg.gather_interior(state[0])
    cells = igg.nx_g() * igg.ny_g() * igg.nz_g()
    if me == 0:
        print(f"nt={nt} steps on {nprocs} device(s): {t:.3f}s "
              f"({cells * nt / t / 1e9:.2f} G cell-updates/s)")
        print(f"P interior: mean {float(P.mean()):+.3e}  "
              f"max |P| {float(np.abs(P).max()):.3e}")

    igg.finalize_global_grid()


if __name__ == "__main__":
    acoustic3D()
