"""3-D heat diffusion with in-situ visualization — port of the reference's
vis example (`/root/reference/examples/diffusion3D_multigpu_CuArrays.jl`,
pattern documented at `reference README.md:108-168`), rebuilt on the io
pipeline: instead of gathering the halo-stripped field to the root every
``nvis`` steps (the reference's O(global)-through-one-host pattern), the
supervised run writes ASYNC sharded snapshots (`snapshot_every=nvis` —
the step loop never waits on disk, no gather ever), and the frames are
assembled AFTER the run by the lazy reader: one O(plane) `read_global`
box per snapshot, pulling only the z-midplane. An in-situ `Stats`
reducer streams max/mean per chunk so the run is monitorable live
without touching the grid either.

Output: diffusion3D.gif if matplotlib is available, else diffusion3D_frames.npy.

Run:  python examples/diffusion3D_multixpu.py [--cpu]
"""

import pathlib
import shutil
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import diffusion_step_local, init_diffusion3d


def diffusion3D():
    cpu = "--cpu" in sys.argv
    nx = 64 if cpu else 128
    nt, nvis = (100, 10) if cpu else (1000, 100)
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, nx, nx)

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    # Supervised run with async snapshots every nvis steps (O(shard) per
    # process, committed in the background) and an in-situ stats reducer
    # (rides the health guard's psum — zero extra collectives). The
    # snapshot root must be ONE directory shared by every process (the
    # multi-host commit protocol stages into a common dir on the shared
    # filesystem — same requirement as checkpoint_dir), so it lives at a
    # deterministic path in the working directory, not a per-process
    # tempdir.
    snaps = "diffusion3D_snapshots"
    if me == 0:  # a previous interrupted run's snapshots must not
        shutil.rmtree(snaps, ignore_errors=True)  # interleave into the gif
    state, reports = igg.run_resilient(
        step, {"T": T, "Cp": Cp}, nt, nt_chunk=nvis, key="diffusion3D_vis",
        snapshot_dir=snaps, snapshot_every=nvis, snapshot_fields=("T",),
        reducers=[igg.Stats("T", which=("max", "mean"))],
        on_reduce=lambda s, v: me == 0 and print(
            f"step {s:4d}  max={v['stats:T']['max']:.3f}  "
            f"mean={v['stats:T']['mean']:.4f}"))

    # Analysis side: assemble ONLY the z-midplane of each snapshot — an
    # O(plane) read per frame, never the global volume (host-only numpy;
    # this part would typically run on a separate analysis machine).
    frames = []
    if me == 0:
        zmid = igg.open_snapshot(
            igg.list_snapshots(snaps)[0][1]).global_shape("T")[2] // 2
        for step_n, path in igg.list_snapshots(snaps):
            snap = igg.open_snapshot(path)
            plane = snap.read_global("T", box=(None, None, (zmid, zmid + 1)))
            frames.append(plane[:, :, 0].copy())

    if me == 0:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.animation as anim
            import matplotlib.pyplot as plt

            fig, ax = plt.subplots()
            im = ax.imshow(frames[0].T, origin="lower", cmap="inferno",
                           vmin=0, vmax=max(f.max() for f in frames))

            def update(f):
                im.set_data(f.T)
                return [im]

            a = anim.FuncAnimation(fig, update, frames=frames, blit=True)
            a.save("diffusion3D.gif", writer="pillow", fps=5)
            print("wrote diffusion3D.gif")
        except Exception as e:  # matplotlib/pillow unavailable
            np.save("diffusion3D_frames.npy", np.stack(frames))
            print(f"wrote diffusion3D_frames.npy ({e.__class__.__name__}: no gif)")

    if me == 0:  # all writers drained before run_resilient returned
        shutil.rmtree(snaps, ignore_errors=True)
    igg.finalize_global_grid()


if __name__ == "__main__":
    diffusion3D()
