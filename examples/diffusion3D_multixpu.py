"""3-D heat diffusion with in-situ visualization — port of the reference's
vis example (`/root/reference/examples/diffusion3D_multigpu_CuArrays.jl`,
pattern documented at `reference README.md:108-168`): every ``nvis`` steps,
gather the halo-stripped field to the root and record a z-midplane heatmap.

Output: diffusion3D.gif if matplotlib is available, else diffusion3D_frames.npy.

Run:  python examples/diffusion3D_multixpu.py [--cpu]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion


def diffusion3D():
    cpu = "--cpu" in sys.argv
    nx = 64 if cpu else 128
    nt, nvis = (100, 10) if cpu else (1000, 100)
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, nx, nx)

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    frames = []
    for it in range(0, nt, nvis):
        T = run_diffusion(T, Cp, p, nvis, nt_chunk=nvis)
        # halo-strip + gather (reference strips manually then gather!s,
        # README.md:143-156; gather_interior does both)
        G = igg.gather_interior(T)
        if me == 0:
            frames.append(G[:, :, G.shape[2] // 2].copy())

    if me == 0:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.animation as anim
            import matplotlib.pyplot as plt

            fig, ax = plt.subplots()
            im = ax.imshow(frames[0].T, origin="lower", cmap="inferno",
                           vmin=0, vmax=max(f.max() for f in frames))

            def update(f):
                im.set_data(f.T)
                return [im]

            a = anim.FuncAnimation(fig, update, frames=frames, blit=True)
            a.save("diffusion3D.gif", writer="pillow", fps=5)
            print("wrote diffusion3D.gif")
        except Exception as e:  # matplotlib/pillow unavailable
            np.save("diffusion3D_frames.npy", np.stack(frames))
            print(f"wrote diffusion3D_frames.npy ({e.__class__.__name__}: no gif)")

    igg.finalize_global_grid()


if __name__ == "__main__":
    diffusion3D()
