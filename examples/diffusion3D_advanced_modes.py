"""The TPU-native modes the reference cannot express, in one script:

1. STOCHASTIC-ROUNDING bf16 storage (`sr=True`): half the HBM traffic of
   f32 with an unbiased store, so long runs track the f32 trajectory
   instead of stagnating (F64_ACCURACY.json: 9.8e-3 vs 0.85 max-rel).
2. COMMUNICATION-AVOIDING deep halos (`comm_every=2`): a 2-wide exchange
   every 2 steps — same wire bytes, half the collectives, bit-identical
   trajectory (tests/test_comm_avoid.py; COMM_AVOID.json).
3. MEASURED overlap: `igg.trace` + `igg.overlap_stats` turn the
   comm/compute schedule into numbers on any backend.

Run:  python examples/diffusion3D_advanced_modes.py [--cpu]
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion


def main():
    cpu = "--cpu" in sys.argv
    nx = 32 if cpu else 192
    nt = 40 if cpu else 400

    # --- 1. stochastic-rounding bf16 vs plain bf16 vs f32 ----------------
    finals = {}
    for tag, dtype, sr in (("f32", jnp.float32, False),
                           ("bf16", jnp.bfloat16, False),
                           ("bf16_sr", jnp.bfloat16, True)):
        igg.init_global_grid(nx, nx, nx, quiet=True)
        T, Cp, p = init_diffusion3d(dtype=dtype, sr=sr)
        out = run_diffusion(T, Cp, p, nt, nt_chunk=nt,
                            impl="xla" if not sr else None)
        g = igg.gather_interior(out)
        finals[tag] = (np.asarray(g).astype(np.float64)
                       if g is not None else None)
        igg.finalize_global_grid()
    if finals["f32"] is not None:
        scale = np.abs(finals["f32"]).max()
        for tag in ("bf16", "bf16_sr"):
            err = np.abs(finals[tag] - finals["f32"]).max() / scale
            print(f"{tag:8s} vs f32 after {nt} steps: max_rel={err:.2e}")

    # --- 2. deep halos: half the collectives, identical numbers ----------
    # (grid with 2-wide halos; nt must be a multiple of comm_every)
    igg.init_global_grid(nx + 2, nx + 2, nx + 2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=jnp.float32, comm_every=2)
    igg.tic()
    out = run_diffusion(T, Cp, p, nt, nt_chunk=nt)
    t = igg.toc(sync_on=out)
    print(f"comm_every=2: {nt} steps in {t:.3f}s "
          f"({nt // 2} exchanges instead of {nt})")
    igg.finalize_global_grid()

    # --- 3. measured overlap of the standard schedule --------------------
    igg.init_global_grid(nx, nx, nx, periodx=1, periody=1, periodz=1,
                         quiet=True)
    T, Cp, p = init_diffusion3d(dtype=jnp.float32, overlap=True)
    run_diffusion(T, Cp, p, 8, nt_chunk=8, impl="xla")     # warm
    with tempfile.TemporaryDirectory() as d:
        with igg.trace(d):
            igg.sync(run_diffusion(T, Cp, p, 8, nt_chunk=8, impl="xla"))
        stats = igg.overlap_stats(d)
    for dev, s in sorted(stats.items()):
        frac = s["overlap_frac"]
        print(f"overlap[{dev}]: hidden "
              f"{s['hidden_comm_us']:.0f}us / {s['comm_us']:.0f}us comm "
              f"({'n/a' if frac is None else f'{100 * frac:.0f}%'})")
    igg.finalize_global_grid()


if __name__ == "__main__":
    main()
