"""3-D pseudo-transient Stokes flow on the implicit global grid.

The BASELINE weak-scaling workload (config 5): iterate the damped PT
system for a buoyant sphere until the global residuals drop below ``tol``
— the convergence-monitored solver loop of the reference's multi-physics
application family (`reference README.md:6-8`). Demonstrates the
multi-array staggered state, the fused Pallas PT-iteration tier, and
`stokes_residuals` (pmax-reduced over the mesh — the collective the
reference's companion apps compute with MPI reductions).

Run:  python examples/stokes3D_multixpu.py [--cpu]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    init_stokes3d, run_stokes, stokes_residuals,
)


def stokes3D():
    cpu = "--cpu" in sys.argv
    nx = 24 if cpu else 96
    max_iters, check_every = (300, 100) if cpu else (6000, 500)
    tol = 5e-4
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, nx, nx)

    state, p = init_stokes3d(dtype=np.float32)
    # warm the chunk + residual programs (functional: the warm run's
    # advanced state is discarded) so tic/toc measures the solve, not XLA
    # compilation — same pattern as the diffusion/acoustic examples
    stokes_residuals(run_stokes(state, p, check_every,
                                nt_chunk=check_every), p)
    igg.tic()
    it = 0
    err = float("inf")
    while it < max_iters:
        state = run_stokes(state, p, check_every, nt_chunk=check_every)
        it += check_every
        err_div, err_mom = stokes_residuals(state, p)
        err = max(err_div, err_mom)
        if me == 0:
            print(f"iters={it:6d}  max|divV|={err_div:.3e}  "
                  f"max|R|={err_mom:.3e}")
        if err < tol:
            break
    t = igg.toc(sync_on=state[0])

    P = igg.gather_interior(state[0])
    if me == 0:
        status = "converged" if err < tol else "max-iters"
        print(f"{status} after {it} PT iterations in {t:.2f}s "
              f"({igg.nx_g()}x{igg.ny_g()}x{igg.nz_g()} global, "
              f"{nprocs} device(s)); P range [{float(P.min()):+.3e}, "
              f"{float(P.max()):+.3e}]")

    igg.finalize_global_grid()


if __name__ == "__main__":
    stokes3D()
