"""3-D heat diffusion on the implicit global grid — port of the reference's
canonical example (`/root/reference/examples/diffusion3D_multicpu_novis.jl` /
`diffusion3D_multigpu_CuArrays_novis.jl`).

One code runs on any mesh: CPU (emulated multi-device), one TPU chip, or a
TPU pod — the device count/topology comes from `init_global_grid` exactly like
the reference's "3 lines to go distributed" UX (`reference README.md:29-33`).

Run:  python examples/diffusion3D_multixpu_novis.py [--cpu]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

if "--cpu" in sys.argv:
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion


def diffusion3D():
    # Physics & numerics (reference example :13-24)
    nx, ny, nz = (64, 64, 64) if "--cpu" in sys.argv else (256, 256, 256)
    nt = 100 if "--cpu" in sys.argv else 1000
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, ny, nz)

    # ICs: two Gaussian anomalies each for Cp and T (reference :34-38)
    T, Cp, p = init_diffusion3d(lam=1.0, cp_min=1.0, lx=10.0, ly=10.0, lz=10.0,
                                dtype=jnp.float32)

    # Whole time loop as one compiled program per chunk (TPU-first hot loop;
    # replaces the reference's per-step broadcast dispatches :41-48).
    # One-chunk warmup (same chunk size ⇒ same cached program) so tic/toc
    # measures steady state, not XLA compilation. run_diffusion returns only
    # after the work drained (data-dependent sync inside run_chunked).
    chunk = max(1, nt // 10)
    run_diffusion(T, Cp, p, chunk, nt_chunk=chunk)
    if nt % chunk:  # remainder chunk is a second program — warm it too
        run_diffusion(T, Cp, p, nt % chunk, nt_chunk=chunk)
    igg.tic()
    T = run_diffusion(T, Cp, p, nt, nt_chunk=chunk)
    t = igg.toc(sync_on=T)

    cells = igg.nx_g() * igg.ny_g() * igg.nz_g()
    G = igg.gather_interior(T)   # collective in multi-host: every process calls it
    if me == 0:
        print(f"nt={nt} steps on {nprocs} device(s): {t:.3f}s "
              f"({cells * nt / t / 1e9:.2f} G cell-updates/s)")
        print(f"T interior mean: {float(G.mean()):.6f}")

    igg.finalize_global_grid()   # reference :50


if __name__ == "__main__":
    diffusion3D()
