"""Accuracy record for the f64 anchor substitution (BASELINE config 3).

The reference's anchor workload is Float64 (its example default,
`/root/reference/examples/diffusion3D_multicpu_novis.jl:26`); this TPU
generation has no native f64 pipeline, so the framework's anchor rows run
f32 (and bf16-with-f32-compute). This script makes that substitution a
MEASURED decision instead of a note: it advances the anchor diffusion
physics in f64 (the ground truth), f32, and bf16 side by side on the
x64-enabled CPU mesh and reports the drift after ``nt`` steps:

    max_rel = max|T_x - T_f64| / max|T_f64|
    l2_rel  = ||T_x - T_f64||_2 / ||T_f64||_2

One JSON line, driver-parseable. The measured numbers are recorded in
`docs/performance.md` ("f64 anchor accuracy"); re-run with
``python bench_f64_accuracy.py [nx] [nt]`` (defaults 48, 400).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    nx = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    nt = int(sys.argv[2]) if len(sys.argv) > 2 else 400
    # the reference legs (f64 ground truth, f32, bf16) must run with an
    # EXACT wire even if the invoking shell exports IGG_HALO_WIRE_DTYPE
    # — an ambient policy would silently corrupt every drift row; the
    # wire legs set it per leg below
    os.environ.pop("IGG_HALO_WIRE_DTYPE", None)
    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))

    finals = {}
    # bf16 runs twice: through the XLA path (native bf16 flux arithmetic)
    # and through the kernel tier in interpret mode (bf16 storage, f32
    # compute — `pallas_stencil._stencil_plane`'s mixed-precision recipe).
    # "f64_bf16ic" integrates the bf16-QUANTIZED initial condition in f64:
    # bf16 legs compared against it isolate ARITHMETIC error from the
    # (irreducible) IC quantization error. The wire legs (5th tuple slot)
    # run f32 state with the quantized halo wire (ISSUE 10): drift vs f64
    # is the accuracy cost of shipping halos as per-slab-scaled int8/int4
    # — the error model docs/performance.md tabulates.
    legs = ((np.float64, "f64", None, False, None),
            (np.float32, "f32", None, False, None),
            (np.float64, "f64_bf16ic", None, True, None),
            (jnp.bfloat16, "bf16_xla", "xla", False, None),
            (jnp.bfloat16, "bf16_kernel", "pallas_interpret", False, None),
            # stochastic-rounding bf16 storage (ops/precision.py): f32
            # compute, unbiased bf16 store — the leg that decides whether
            # bf16 is a correctness-preserving mode or only a bandwidth
            # study (round-4 verdict)
            (jnp.bfloat16, "bf16_sr", "sr", False, None),
            (np.float32, "int8_wire", None, False, "int8"),
            (np.float32, "int4_wire", None, False, "int4"))
    for dtype, tag, impl, bf16_ic, wire in legs:
        igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=1, periody=1, periodz=1,
                             quiet=True)
        # identical physics: ICs are built in the target dtype by the model,
        # but dt/dx come from f64 host scalars either way
        if bf16_ic:
            Tb, Cpb, _ = init_diffusion3d(dtype=jnp.bfloat16)
            _, _, p = init_diffusion3d(dtype=dtype)
            T = igg.device_put_g(np.asarray(Tb).astype(dtype))
            Cp = igg.device_put_g(np.asarray(Cpb).astype(dtype))
        else:
            T, Cp, p = init_diffusion3d(dtype=dtype, sr=(impl == "sr"))
        if wire is not None:
            os.environ["IGG_HALO_WIRE_DTYPE"] = wire
        try:
            out = run_diffusion(T, Cp, p, nt, nt_chunk=max(1, nt // 4),
                                impl=None if impl == "sr" else impl)
        finally:
            if wire is not None:
                os.environ.pop("IGG_HALO_WIRE_DTYPE", None)
        finals[tag] = np.asarray(igg.gather_interior(out), dtype=np.float64)
        igg.finalize_global_grid()

    scale = float(np.max(np.abs(finals["f64"])))
    l2 = float(np.linalg.norm(finals["f64"]))
    drift = {}
    for tag, ref_tag in (("f32", "f64"), ("f64_bf16ic", "f64"),
                         ("bf16_xla", "f64_bf16ic"),
                         ("bf16_kernel", "f64_bf16ic"),
                         ("bf16_sr", "f64_bf16ic"),
                         ("int8_wire", "f64"), ("int4_wire", "f64")):
        d = finals[tag] - finals[ref_tag]
        drift[tag] = {
            "vs": ref_tag,
            "max_rel": float(np.max(np.abs(d)) / scale),
            "l2_rel": float(np.linalg.norm(d) / l2),
        }

    print(json.dumps({
        "metric": "diffusion3D_f64_substitution_drift",
        "value": drift["f32"]["max_rel"],
        "unit": f"max|T_f32-T_f64|/max|T_f64| after nt={nt}, global grid "
                f"{'x'.join(str(s) for s in finals['f64'].shape)}",
        "drift": drift,
        "nx": nx, "nt": nt,
        "note": "anchor physics advanced in f64/f32/bf16 side by side on "
                "the x64 CPU mesh; f32 drift (vs f64) is the accuracy cost "
                "of the TPU anchor substitution (BASELINE config 3). "
                "f64_bf16ic (vs f64) is the irreducible bf16 IC "
                "quantization; bf16_xla / bf16_kernel compare against it, "
                "isolating ARITHMETIC drift: native bf16 flux arithmetic "
                "vs the kernel tier's bf16-storage/f32-compute recipe vs "
                "stochastic-rounding storage (bf16_sr, ops/precision.py). "
                "int8_wire / int4_wire (vs f64) run f32 state with the "
                "quantized halo wire (ISSUE 10, per-slab-scaled payloads): "
                "the drift bound the quant-marked accuracy tier asserts",
    }))


if __name__ == "__main__":
    main()
