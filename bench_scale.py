"""Scale-up dryrun: does the compiled exchange behave toward pod scale?

The north star is v5p-256 (`BASELINE.json`); real hardware here is one chip.
This harness builds 8/27/64-device VIRTUAL CPU meshes (one subprocess per
config — the device count must be fixed before backend init), plus a
4-process x 16-device hybrid-DCN mesh (`IGG_TPU_DCN_AXES=z`, the multi-slice
layout), and records for each:

- mesh construction + `init_global_grid` wall time,
- lower+compile wall time of the flagship whole-step program (stencil +
  inline halo ppermutes),
- the optimized HLO's collective-permute count (SPMD: must stay EXACTLY one
  pair per exchanging axis — 6 — independent of device count; a count that
  grows with N means the program stopped being scale-free),
- optimized HLO size and one-step execution wall time (virtual mesh, so an
  emulation number, not a perf claim).

Output: one JSON line per config + a summary line; `SCALE_DRYRUN.json`
committed at the repo root is this script's captured output
(`python bench_scale.py > SCALE_DRYRUN.json`).

The per-shard program is O(1) in device count by construction (shard_map
SPMD) — what CAN grow is compile time (XLA re-verifies the mesh) and mesh
bookkeeping; that growth curve is what this artifact pins.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

# One measurement template; the single- and multi-process variants differ
# only in their preamble (device count / distributed init) and row extras,
# injected via format fields — so the measured quantities can never drift
# between the two.
_MEASURE_TEMPLATE = """
import json, os, sys, time

{preamble}
import numpy as np

sys.path.insert(0, "/root/repo")
import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, make_run

dims = [int(d) for d in igg.dims_create(n, (0, 0, 0))]
t0 = time.perf_counter()
igg.init_global_grid(8, 8, 8, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                     periodx=1, periody=1, periodz=1, quiet=True,
                     **init_kw)
t_init = time.perf_counter() - t0

T, Cp, p = init_diffusion3d(dtype=np.float32)
run = make_run(p, nt_chunk=1, impl="xla")
t0 = time.perf_counter()
compiled = run.lower(T, Cp).compile()
t_compile = time.perf_counter() - t0
hlo = compiled.as_text()
permutes = hlo.count("collective-permute-start") or \\
    hlo.count("collective-permute(")

out = jax.block_until_ready(run(T, Cp))
t0 = time.perf_counter()
out = jax.block_until_ready(run(*out))
t_exec = time.perf_counter() - t0

row = {{
    "n_devices": n, "dims": dims, "t_init_s": round(t_init, 3),
    "t_compile_s": round(t_compile, 3),
    "collective_permutes": permutes,
    "hlo_bytes": len(hlo), "t_exec_s": round(t_exec, 4),
}}
row.update(extras)
if emit:
    print(prefix + json.dumps(row), flush=True)
"""

_CHILD = _MEASURE_TEMPLATE.format(preamble=textwrap.dedent("""
    n = int(sys.argv[1])
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    init_kw = {}
    extras = {"processes": 1}
    emit, prefix = True, ""
"""))

_CHILD_MP = _MEASURE_TEMPLATE.format(preamble=textwrap.dedent("""
    pid, nproc, port, ndev = (int(sys.argv[1]), int(sys.argv[2]),
                              sys.argv[3], int(sys.argv[4]))
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    os.environ["IGG_TPU_DCN_AXES"] = "z"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)
    n = nproc * ndev
    init_kw = {"init_dist": False, "reorder": 0}
    extras = {"processes": nproc, "dcn_axes": "z"}
    emit, prefix = (pid == 0), "SCALE_MP "
"""))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = ""
    return env


def run_single(n: int, tmp: str, timeout: int = 900):
    path = os.path.join(tmp, f"scale_child_{n}.py")
    with open(path, "w") as f:
        f.write(_CHILD)
    proc = subprocess.run([sys.executable, path, str(n)],
                          capture_output=True, text=True, timeout=timeout,
                          env=_clean_env(), cwd="/root/repo")
    for ln in proc.stdout.splitlines():
        if ln.strip().startswith("{"):
            return json.loads(ln)
    return {"n_devices": n, "error":
            (proc.stderr or proc.stdout or "no output")[-800:]}


def run_multiprocess(nproc: int, ndev: int, tmp: str, timeout: int = 900):
    path = os.path.join(tmp, "scale_child_mp.py")
    with open(path, "w") as f:
        f.write(_CHILD_MP)
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, path, str(pid), str(nproc), str(port), str(ndev)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_clean_env(), cwd="/root/repo") for pid in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    row = None
    for out in outs:
        for ln in out.splitlines():
            if ln.startswith("SCALE_MP "):
                row = json.loads(ln[len("SCALE_MP "):])
    if row is None:
        row = {"n_devices": nproc * ndev, "processes": nproc, "error":
               "\\n---\\n".join(o[-400:] for o in outs)}
    return row


def main() -> None:
    import tempfile

    single_ns = [int(x) for x in
                 os.environ.get("IGG_SCALE_NS", "8,27,64").split(",")]
    rows = []

    def guarded(fn, n, *args):
        # a hung config must become an error ROW, not a traceback that
        # loses the summary and the remaining configs
        try:
            return fn(*args)
        except Exception as e:
            return {"n_devices": n, "error": f"{type(e).__name__}: {e}"}

    # The 4-process x 16-device distributed-CPU config exercises a real
    # jax.distributed coordinator — valuable evidence, but a coordinator
    # flake or slow shared runner must not redden a build that only asked
    # for the trimmed single-process sweep.  IGG_SCALE_MP=1/0 forces it
    # on/off; otherwise it runs only for the full (untrimmed) sweep
    # (ADVICE r4: CI trims with IGG_SCALE_NS and must not gate on it).
    mp_env = os.environ.get("IGG_SCALE_MP", "").strip().lower()
    if mp_env in ("1", "true", "yes"):
        run_mp = True
    elif mp_env in ("0", "false", "no"):
        run_mp = False
    elif mp_env:
        sys.stderr.write(f"[bench_scale] ignoring IGG_SCALE_MP={mp_env!r} "
                         "(expected 1/0)\n")
        run_mp = "IGG_SCALE_NS" not in os.environ
    else:
        run_mp = "IGG_SCALE_NS" not in os.environ

    with tempfile.TemporaryDirectory() as tmp:
        for n in single_ns:
            rows.append(guarded(run_single, n, n, tmp))
            print(json.dumps(rows[-1]), flush=True)
        if run_mp:
            rows.append(guarded(run_multiprocess, 64, 4, 16, tmp))
            print(json.dumps(rows[-1]), flush=True)

    ok_rows = [r for r in rows if "error" not in r]
    permutes = sorted({r["collective_permutes"] for r in ok_rows})
    summary = {
        "metric": "scale_dryrun_compile_growth",
        "value": (max(r["t_compile_s"] for r in ok_rows) /
                  min(r["t_compile_s"] for r in ok_rows)) if ok_rows else None,
        "unit": "max/min compile time over configs",
        "permute_counts": permutes,
        "scale_free_program": permutes == [6],
        "configs_ok": len(ok_rows), "configs_total": len(rows),
        "note": "SPMD per-shard program: permute count must stay 6 (one "
                "pair per axis) at every device count; compile time growth "
                "bounds the v5p-256 extrapolation",
    }
    if not run_mp:
        # record the skip so a trimmed sweep cannot read as full evidence
        summary["mp_skipped"] = ("4-process DCN config not run "
                                 "(trimmed sweep; set IGG_SCALE_MP=1 to "
                                 "include it)")
    print(json.dumps(summary), flush=True)
    # CI gate (same contract as the other benches' IGG_BENCH_STRICT): red
    # build when a config failed or the program stopped being scale-free.
    if os.environ.get("IGG_BENCH_STRICT") == "1" and not (
            len(ok_rows) == len(rows) and summary["scale_free_program"]):
        sys.exit(1)


if __name__ == "__main__":
    main()
