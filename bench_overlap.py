"""Measure the comm/compute overlap the framework structurally guarantees.

`hide_communication` (ops/overlap.py) reorders each step so the halo
ppermutes are SSA-independent of the interior compute — the structural
guard is tests/test_hlo_audit.py. This harness measures what that buys at
runtime (round-4 verdict: the hidden-communication *fraction* had never
been measured anywhere):

- trace a multi-step diffusion chunk with ``overlap=True`` and again with
  ``overlap=False`` (same shapes, same chunk program length, both warmed
  so no compile lands in the window);
- run `igg.overlap_stats` on each capture: hidden vs exposed collective
  time, per device plane on hardware or aggregated over the runtime
  thread pool on the virtual CPU mesh (see `_host_overlap_stats`);
- cross-check with the WALL-CLOCK per-step delta of the same two programs
  (two-point windows), which is transport-independent evidence of the
  benefit.

Emits ONE JSON line:
  {"metric": "halo_overlap_hidden_frac", "value": <hidden/comm, overlap on>,
   "overlap_on": {...}, "overlap_off": {...},
   "step_ms_on": ..., "step_ms_off": ..., ...}

Usage: python bench_overlap.py --cpu    (8-device virtual mesh)
       python bench_overlap.py          (real devices)
"""

from __future__ import annotations

import sys
import tempfile

import bench_util


def _agg(stats: dict) -> dict:
    """One record from overlap_stats entries: PER-PLANE MEANS for the
    time fields (devices run the same SPMD program ~in lockstep, so a sum
    would scale with plane count and misread multi-plane captures), a
    comm-weighted overall hidden fraction, and ``exposed_comm_us_max`` —
    the critical-path exposure, the SAME statistic `bench_weak.py` emits
    as ``exposed_comm_ms_per_step`` so the two artifacts compare. The CPU
    fallback returns one aggregate entry, so there this is the
    identity."""
    tot = {"busy_us": 0.0, "compute_us": 0.0, "comm_us": 0.0,
           "hidden_comm_us": 0.0, "exposed_comm_us": 0.0}
    for s in stats.values():
        for k in tot:
            tot[k] += s[k]
    frac = (tot["hidden_comm_us"] / tot["comm_us"]
            if tot["comm_us"] else None)
    n = max(1, len(stats))
    tot = {k: v / n for k, v in tot.items()}
    tot["overlap_frac"] = frac
    tot["exposed_comm_us_max"] = max(
        (s["exposed_comm_us"] for s in stats.values()), default=0.0)
    tot["planes"] = sorted(stats)
    return tot


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import init_diffusion3d, make_run

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    nx, steps = (48, 24) if cpu else (256, 60)

    def measure(overlap: bool):
        igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=1, periody=1, periodz=1,
                             quiet=True)
        try:
            T, Cp, p = init_diffusion3d(dtype=np.float32, overlap=overlap)
            # the XLA broadcast step is the one hide_communication reorders;
            # the Pallas tier fuses the exchange INTO the kernel instead
            run = make_run(p, nt_chunk=steps, impl="xla")
            igg.sync(run(T, Cp))           # warm: no compile in the window
            with tempfile.TemporaryDirectory() as d:
                with igg.trace(d):
                    igg.sync(run(T, Cp))
                stats = _agg(igg.overlap_stats(d))

            def chunk(c):
                igg.sync(make_run(p, nt_chunk=c, impl="xla")(T, Cp))

            sec = bench_util.two_point(chunk, steps, 3 * steps)
            return stats, sec * 1e3
        finally:
            igg.finalize_global_grid()

    on, ms_on = measure(True)
    off, ms_off = measure(False)
    bench_util.emit({
        "metric": "halo_overlap_hidden_frac",
        "value": on["overlap_frac"],
        "unit": "hidden_comm/comm (overlap=True trace)",
        "steps_traced": steps,
        "overlap_on": on,
        "overlap_off": off,
        "exposed_comm_ms_per_step_on":
            on["exposed_comm_us_max"] / steps / 1e3,
        "exposed_comm_ms_per_step_off":
            off["exposed_comm_us_max"] / steps / 1e3,
        "step_ms_on": ms_on,
        "step_ms_off": ms_off,
        "note": ("hide_communication A/B on the XLA step: trace-derived "
                 "hidden/exposed collective time + wall-clock per-step "
                 "cross-check; on --cpu the stats come from the runtime "
                 "thread pool (CPU:threadpool) — virtual devices share "
                 "host cores, so exposed time there bounds scheduling, "
                 "not ICI"),
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("halo_overlap_hidden_frac",
                                    "hidden_comm/comm")
